"""Batched multi-scenario engine: one vmapped round for a whole bucket.

The bucket's scenarios share one compiled program (packer.py's
signature contract); their states and topology tables stack on a
leading scenario axis and :func:`aligned.aligned_round` — THE round
implementation every aligned engine shares — runs under ``jax.vmap``
with per-scenario overrides for the two seed-derived inputs the solo
engine reads as statics (the liveness hash seed and the staggered
message-source table).  Everything else that is per-scenario already
flows through arrays: the PRNG chain (``state.key``), the byzantine
draw (``state.byz_w``), the overlay tables, and the fault gates (keyed
on ``(plan-seed, round, global id)``, identical solo or batched).

Convergence masking + bucket early-exit: the lockstep scan checks every
scenario's census coverage EVERY round (the done flags live on-device,
so per-round checking costs no host sync — unlike the solo engine's
check_every barrier amortization) and freezes a converged scenario's
state/topology in place, so its recorded trajectory ends at its exact
convergence round while stragglers run on.  The host loop polls the
done flags once per ``check_every``-round chunk and stops the bucket as
soon as every scenario has converged.

Bitwise contract (tests/test_fleet.py): scenario ``i``'s unpacked
``SimResult`` — state, mutated topology, and every per-round metric —
is bit-identical to ``sims[i].run(rounds_i)`` on the solo engine.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

import jax
import jax.numpy as jnp

from p2p_gossipprotocol_tpu.aligned import (ALIGNED_TOPO_LEAVES,
                                            AlignedTopology, aligned_round)
from p2p_gossipprotocol_tpu.fleet.packer import bucket_signature
from p2p_gossipprotocol_tpu.state import stagger_sched_end

#: metric keys of aligned_round's census dict, in emission order, with
#: the dtype each arrives in from the solo engine's scan (evictions is
#: the one int — the rest ride the exact-popcount-pair float32 path).
#: The unpacked histories keep these dtypes so a fleet SimResult is
#: indistinguishable from a solo one, array dtypes included.
METRIC_DTYPES = {"coverage": np.float32, "deliveries": np.float32,
                 "frontier_size": np.float32, "live_peers": np.float32,
                 "evictions": np.int32, "redeliveries": np.float32}
METRIC_KEYS = tuple(METRIC_DTYPES)


def stack_topologies(topos: list[AlignedTopology],
                     template: AlignedTopology) -> AlignedTopology:
    """One AlignedTopology whose array leaves carry a leading scenario
    axis; static fields come from the template (none of them is read by
    the round itself — ``rows`` derives from the leaf shapes, which are
    per-scenario inside the vmap)."""
    kw = {k: jnp.stack([getattr(t, k) for t in topos])
          for k in ALIGNED_TOPO_LEAVES}
    ytab = (None if template.ytab is None
            else jnp.stack([t.ytab for t in topos]))
    return AlignedTopology(**kw, ytab=ytab, n_peers=template.n_peers,
                           n_slots=template.n_slots,
                           rowblk=template.rowblk,
                           roll_groups=template.roll_groups,
                           reuse_leak=template.reuse_leak)


def _unstack_topology(btopo: AlignedTopology, i: int,
                      solo: AlignedTopology) -> AlignedTopology:
    """Scenario ``i``'s slice of the batched topology, carrying ITS solo
    statics back (n_peers differs per scenario within a bucket)."""
    kw = {k: getattr(btopo, k)[i] for k in ALIGNED_TOPO_LEAVES}
    return AlignedTopology(**kw,
                           ytab=(None if btopo.ytab is None
                                 else btopo.ytab[i]),
                           n_peers=solo.n_peers, n_slots=solo.n_slots,
                           rowblk=solo.rowblk,
                           roll_groups=solo.roll_groups,
                           reuse_leak=solo.reuse_leak)


def bucket_class_for(sim):
    """The bucket class that batches/serves this simulator kind: a sim
    may carry its own (``RealGraphSimulator`` sets ``_bucket_class`` —
    the dispatch stays attribute-based so this module never imports
    realgraph); the aligned family is the default."""
    return getattr(sim, "_bucket_class", FleetBucket)


def _freeze(done, old, new):
    """Per-leaf select: a done scenario keeps its frozen value."""
    d = done.reshape(done.shape + (1,) * (new.ndim - 1))
    return jnp.where(d, old, new)


@dataclass
class BucketResult:
    """One bucket's unpacked outcome.

    ``results[i]`` is scenario i's :class:`sim.SimResult` covering
    rounds ``[0, rounds_run[i])`` — its history truncated at its own
    convergence round, bitwise-equal to the solo engine's.  ``wall_s``
    is the BUCKET's wall-clock (shared by every scenario it served —
    the whole point of batching); per-scenario attribution is
    ``wall_s / len(results)``."""

    results: list                      # list[sim.SimResult]
    rounds_run: np.ndarray             # int32[B] rounds each scenario ran
    converged: np.ndarray              # bool [B] reached the target
    wall_s: float = 0.0
    interrupted: bool = False          # should_stop fired mid-bucket


@dataclass
class FleetBucket:
    """A signature-identical scenario batch, runnable as one program.

    ``sims`` are the exact solo simulators (spec.py builds them through
    ``AlignedSimulator.from_config``, the same path the CLI takes) —
    the bucket only ever *batches* them, never rebuilds or reshapes
    them, which is what makes the bitwise-parity contract provable.

    The serving plane (serve/) keeps a bucket RESIDENT: ``init_idle``
    stacks the template into an all-done batch, :meth:`admit_into`
    scatters one scenario's state/topology/seed/srcs into a freed slot
    between chunks, and :meth:`mark_done` retires a slot.  All three
    are value-only array updates against the one cached chunk program —
    ``trace_count`` counts chunk retraces so the serving tests can
    assert admission never recompiles.
    """

    sims: list                         # list[AlignedSimulator]
    _chunk_cache: dict = field(default_factory=dict, repr=False)
    #: chunk-program retrace counter: the traced body bumps it once per
    #: jit trace, so a resident bucket can PROVE slot-swap admission
    #: stayed compilation-free (the serving plane's acceptance gate).
    trace_count: int = field(default=0, repr=False)

    #: per-kind metric dtype table (class attributes so engine-specific
    #: buckets — realgraph — override them; the serving plane and the
    #: result unpack read them off the bucket, never the module)
    metric_dtypes = METRIC_DTYPES
    metric_keys = METRIC_KEYS
    #: serve-salvage manifest kind tag (per-bucket payload dispatch)
    persist_kind = "aligned"

    def __post_init__(self):
        if not self.sims:
            raise ValueError("a fleet bucket needs at least one scenario")
        sig = bucket_signature(self.sims[0])
        for s in self.sims[1:]:
            if bucket_signature(s) != sig:
                raise ValueError(
                    "fleet bucket scenarios must share one program "
                    "signature (packer.pack groups them)")
        self.template = self.sims[0]
        self._seeds = jnp.asarray([s.seed for s in self.sims], jnp.int32)
        # staggered-generation source tables (per-scenario: the plan is
        # seed- and byzantine-derived); harmless constants when stagger
        # is off (aligned_round never touches them then)
        if self.template.message_stagger > 0:
            self._srcs = jnp.stack(
                [self._srcs_row_of(s) for s in self.sims])
        else:
            self._srcs = jnp.zeros((len(self.sims), 1), jnp.int32)
        self._sched_end = stagger_sched_end(
            self.template._n_honest, self.template.message_stagger)

    # -- per-kind hooks (RealGraphBucket overrides these) ---------------
    def _srcs_row_of(self, s):
        """One scenario's staggered message-source row."""
        return s._message_plan()[1]

    def _one_round(self):
        """The per-slot round fn the chunk vmaps:
        ``(state, topo, seed, srcs) -> (state', topo', metrics)``."""
        tmpl = self.template

        def one(state, topo, seed, srcs):
            grows = jnp.arange(topo.rows, dtype=jnp.int32)
            return aligned_round(
                tmpl, state, topo, grows=grows, t_off=jnp.int32(0),
                gather=lambda x: x, reduce=lambda x: x,
                hash_seed=seed, msg_srcs=srcs)
        return one

    def unstack_topo(self, btopo, i: int, solo_topo):
        """Slot ``i``'s solo topology slice."""
        return _unstack_topology(btopo, i, solo_topo)

    def stack_topos(self):
        """Every scenario's solo topology, stacked along the slot axis
        (the inverse of :meth:`unstack_topo`; the salvage-restore path
        rebuilds statics through this before overlaying the persisted
        mutable leaves)."""
        return stack_topologies([s.topo for s in self.sims],
                                self.template.topo)

    def persist_arrays(self, bstate, btopo) -> dict:
        """Every mutable array leaf a serve salvage must persist for
        this bucket kind, keyed ``state/<leaf>`` / ``topo/<leaf>``
        (serve/service.py writes them; :meth:`restore_arrays` is the
        inverse).  For aligned buckets that is the AlignedState leaves
        (+ optional strikes) and the rewired ``colidx`` lanes."""
        out = {f"state/{k}": getattr(bstate, k)
               for k in ("seen_w", "frontier_w", "alive_b", "byz_w",
                         "key", "round")}
        if bstate.strikes is not None:
            out["state/strikes"] = bstate.strikes
        out["topo/colidx"] = btopo.colidx
        return out

    def restore_arrays(self, btopo, payload: dict):
        """Rebuild (bstate, btopo) from a salvage payload dict — the
        inverse of :meth:`persist_arrays`, against the freshly
        re-admitted bucket's topology."""
        from p2p_gossipprotocol_tpu.aligned import AlignedState

        state = AlignedState(
            **{k: jnp.asarray(payload[f"state/{k}"])
               for k in ("seen_w", "frontier_w", "alive_b", "byz_w",
                         "key", "round")},
            strikes=(jnp.asarray(payload["state/strikes"])
                     if "state/strikes" in payload else None))
        btopo = btopo.replace(
            colidx=jnp.asarray(payload["topo/colidx"]))
        return state, btopo

    @property
    def size(self) -> int:
        return len(self.sims)

    # ------------------------------------------------------------------
    def init(self):
        """(bstate, btopo): every scenario's solo init_state / topology,
        stacked — bit-identical per scenario by construction."""
        bstate = jax.tree.map(lambda *xs: jnp.stack(xs),
                              *[s.init_state() for s in self.sims])
        return bstate, self.stack_topos()

    # ------------------------------------------------------------------
    @classmethod
    def for_serving(cls, sim, slots: int) -> "FleetBucket":
        """A ``slots``-wide resident bucket seeded from one template
        scenario: every slot holds a copy of the template (inert once
        ``init_idle`` marks it done), and the serving plane scatters
        real scenarios in via :meth:`admit_into`.  The template fixes
        the bucket's program signature; admission never changes it."""
        if slots < 1:
            raise ValueError("a serving bucket needs at least one slot")
        return cls([sim] * slots)

    def init_idle(self):
        """(bstate, btopo, done): the template's world tiled across
        every slot, all marked done — inert filler, ready for
        admissions.  Tiles ONE init_state/topology instead of calling
        :meth:`init`'s per-sim path (a serving bucket's slots all start
        as the same template, and at 64 slots x 64k peers the 64
        redundant init_state computations dominated server start)."""
        st = self.template.init_state()
        bstate = jax.tree.map(lambda x: jnp.stack([x] * self.size), st)
        topo = self.template.topo
        kw = {k: jnp.stack([getattr(topo, k)] * self.size)
              for k in ALIGNED_TOPO_LEAVES}
        btopo = AlignedTopology(
            **kw,
            ytab=(None if topo.ytab is None
                  else jnp.stack([topo.ytab] * self.size)),
            n_peers=topo.n_peers, n_slots=topo.n_slots,
            rowblk=topo.rowblk, roll_groups=topo.roll_groups,
            reuse_leak=topo.reuse_leak)
        return bstate, btopo, jnp.ones(self.size, bool)

    def admit_args(self, sim):
        """Host-side per-slot payload for :meth:`admit_into`: the
        scenario's exact solo init state, its overlay leaves, liveness
        hash seed, and staggered source row — everything per-scenario
        the vmapped round reads.  Built OUTSIDE the scatter so the
        serving loop can stage the next admissions while the current
        chunk still runs on-device (host->HBM overlap)."""
        state = sim.init_state()
        leaves = {k: getattr(sim.topo, k) for k in ALIGNED_TOPO_LEAVES}
        ytab = sim.topo.ytab
        seed = jnp.int32(sim.seed)
        if self.template.message_stagger > 0:
            srcs_row = sim._message_plan()[1]
        else:
            srcs_row = jnp.zeros((1,), jnp.int32)
        return state, leaves, ytab, seed, srcs_row

    def _admit_fn(self):
        """Cached jitted scatter: write one scenario's world into slot
        ``slot`` of the resident batch and un-done the slot.  ``slot``
        is a traced scalar, so admissions at different slots share one
        compilation; on accelerator backends the batch buffers are
        donated (the slot swap reuses the retiree's HBM)."""
        if "admit" in self._chunk_cache:
            return self._chunk_cache["admit"]
        has_ytab = self.template.topo.ytab is not None

        def admit(bstate, btopo, done, seeds, srcs, slot,
                  nstate, nleaves, nytab, seed, srcs_row):
            bstate = jax.tree.map(lambda b, n: b.at[slot].set(n),
                                  bstate, nstate)
            upd = {k: getattr(btopo, k).at[slot].set(nleaves[k])
                   for k in ALIGNED_TOPO_LEAVES}
            if has_ytab:
                upd["ytab"] = btopo.ytab.at[slot].set(nytab)
            btopo = btopo.replace(**upd)
            done = done.at[slot].set(False)
            seeds = seeds.at[slot].set(seed)
            srcs = srcs.at[slot].set(srcs_row)
            return bstate, btopo, done, seeds, srcs

        # donation is a no-op (with a warning) on CPU — only ask for it
        # where the runtime can honor it
        donate = (jax.default_backend() not in ("cpu",))
        fn = jax.jit(admit, donate_argnums=(0, 1, 2, 3, 4) if donate
                     else ())
        self._chunk_cache["admit"] = fn
        return fn

    def admit_into(self, bstate, btopo, done, seeds, srcs, slot: int,
                   sim=None, payload=None):
        """Scatter ``sim`` (or a pre-staged :meth:`admit_args` payload)
        into ``slot``; returns the updated (bstate, btopo, done, seeds,
        srcs).  The admitted scenario must share the bucket signature —
        the serving scheduler guarantees it, and the check here keeps a
        mis-routed admission a named error instead of silent state
        corruption."""
        if payload is None:
            if bucket_signature(sim) != bucket_signature(self.template):
                raise ValueError(
                    "admitted scenario does not match the bucket's "
                    "program signature (scheduler routing bug)")
            payload = self.admit_args(sim)
        state, leaves, ytab, seed, srcs_row = payload
        if ytab is None:       # jit wants a concrete operand either way
            ytab = jnp.zeros((1,), jnp.int32)
        return self._admit_fn()(bstate, btopo, done, seeds, srcs,
                                jnp.int32(slot), state, leaves, ytab,
                                seed, srcs_row)

    def extract_slot_payload(self, bstate, btopo, seeds, srcs,
                             slot: int):
        """The inverse of :meth:`admit_args`, read from the LIVE batch:
        slot ``slot``'s current state, overlay leaves, liveness seed
        and source row, in exactly the payload shape
        :meth:`admit_into` scatters.  This is the migration primitive
        the serving plane's autoscaler uses to move an in-flight
        occupant between bucket widths (round 17): the occupant's
        world — PRNG chain, rewired lanes, fault-gate inputs included —
        is carried bit-for-bit, so the resumed trajectory in the new
        batch is the same one the old batch would have computed (the
        vmapped round is per-slot independent, the PR 4 contract)."""
        state = jax.tree.map(lambda x: x[slot], bstate)
        leaves = {k: getattr(btopo, k)[slot]
                  for k in ALIGNED_TOPO_LEAVES}
        ytab = None if btopo.ytab is None else btopo.ytab[slot]
        return state, leaves, ytab, seeds[slot], srcs[slot]

    def mark_done(self, done, slot: int):
        """Retire ``slot``: the done mask freezes it on-device (inert —
        the convergence-masking machinery, reused as the slot-free
        primitive)."""
        if "mark" not in self._chunk_cache:
            self._chunk_cache["mark"] = jax.jit(
                lambda d, s: d.at[s].set(True))
        return self._chunk_cache["mark"](done, jnp.int32(slot))

    # ------------------------------------------------------------------
    def _chunk_fn(self, length: int, target: float | None):
        """Compiled ``length``-round lockstep chunk with in-scan
        convergence masking; cached per (length, target)."""
        key = (length, target)
        if key in self._chunk_cache:
            return self._chunk_cache[key]
        sched_end = self._sched_end

        vstep = jax.vmap(self._one_round())

        def chunk(bstate, btopo, done, seeds, srcs):
            # trace-time only: one bump per compilation of this chunk
            # program — the serving tests read it to assert slot-swap
            # admission stayed compilation-free
            self.trace_count += 1

            def body(carry, _):
                bs, bt, dn = carry
                ns, nt, m = vstep(bs, bt, seeds, srcs)
                # convergence masking: a done scenario's world is
                # frozen (state, PRNG chain, rewired lane tables), so
                # its trajectory ends at its exact convergence round.
                # With no target the mask is all-False and the select
                # is the identity — the fixed-round path compiles to
                # the same values the solo scan produces.
                ns = jax.tree.map(lambda o, n: _freeze(dn, o, n), bs, ns)
                nt = jax.tree.map(lambda o, n: _freeze(dn, o, n), bt, nt)
                if target is not None:
                    # solo run_to_coverage's stop condition, per
                    # scenario: census coverage at target AND the
                    # stagger schedule fully emitted.
                    dn = dn | ((m["coverage"] >= target)
                               & (ns.round >= sched_end))
                return (ns, nt, dn), (m, dn)

            (bs, bt, dn), (ys, dhist) = jax.lax.scan(
                body, (bstate, btopo, done), None, length=length)
            return bs, bt, dn, ys, dhist

        fn = jax.jit(chunk)
        self._chunk_cache[key] = fn
        return fn

    # ------------------------------------------------------------------
    def run(self, rounds: int, target: float | None = None,
            check_every: int = 8, state=None, topo=None, done=None,
            hist: dict | None = None, rounds_done: int = 0,
            should_stop=None, after_chunk=None) -> BucketResult:
        """Serve the whole bucket for up to ``rounds`` rounds.

        ``target`` enables convergence masking + early exit: the bucket
        stops at the first chunk boundary where EVERY scenario has
        converged (each at its own exact round — the masking is
        per-round, on-device).  ``target=None`` runs the fixed-round
        lockstep scan, the bitwise twin of every solo ``run(rounds)``.

        ``state``/``topo``/``done``/``hist``/``rounds_done`` resume a
        salvaged bucket mid-flight (driver.py persists them);
        ``should_stop`` is polled between chunks and ``after_chunk``
        receives ``(bstate, btopo, done, hist, rounds_done)`` after
        each chunk — the checkpoint seam.
        """
        if check_every < 1:
            raise ValueError("check_every must be >= 1")
        from p2p_gossipprotocol_tpu.sim import SimResult

        B = self.size
        if state is None or topo is None:
            state, topo = self.init()
        if done is None:
            done = jnp.zeros(B, bool)
        hist = dict(hist) if hist else {
            k: np.zeros((0, B), dt)
            for k, dt in self.metric_dtypes.items()}
        conv = hist.pop("_converged_round", np.zeros(B, np.int64) - 1)
        conv = np.asarray(conv, np.int64)
        t0 = time.perf_counter()
        interrupted = False
        while rounds_done < rounds:
            if should_stop is not None and should_stop():
                interrupted = True
                break
            if target is not None and bool(np.asarray(
                    jax.device_get(done)).all()):
                break                      # bucket early-exit
            step = min(check_every, rounds - rounds_done)
            fn = self._chunk_fn(step, target)
            # telemetry: host-side span + counters around the already-
            # scheduled chunk — never inside the compiled program, so
            # trace_count and results are identical on or off
            from p2p_gossipprotocol_tpu import telemetry

            with telemetry.span("chunk", kind="fleet", rounds=step,
                                batch=B, start_round=rounds_done):
                state, topo, done, ys, dhist = fn(state, topo, done,
                                                  self._seeds,
                                                  self._srcs)
                ys = {k: np.asarray(jax.device_get(ys[k]))
                      for k in self.metric_keys}
            telemetry.counter_add("fleet_rounds_total", step)
            telemetry.counter_add("fleet_scenario_rounds_total",
                                  step * B)
            dh = np.asarray(jax.device_get(dhist))       # [step, B] bool
            hist = {k: np.concatenate([hist[k], ys[k]]) for k in ys}
            # first round (1-indexed, global) each scenario converged
            for j in range(step):
                newly = dh[j] & (conv < 0)
                conv[newly] = rounds_done + j + 1
            rounds_done += step
            if after_chunk is not None:
                after_chunk(state, topo, done,
                            {**hist, "_converged_round": conv},
                            rounds_done)
        # ensure completion before reading the clock (device_get above
        # already synchronizes each chunk; this is the zero-chunk case)
        jax.block_until_ready(state.round)
        wall = time.perf_counter() - t0

        converged = conv > 0
        rounds_run = np.where(converged, conv, rounds_done).astype(
            np.int64)
        results = []
        for i, solo in enumerate(self.sims):
            r_i = int(rounds_run[i])
            st_i = jax.tree.map(lambda x: x[i], state)
            tp_i = self.unstack_topo(topo, i, solo.topo)
            results.append(SimResult(
                state=st_i, topo=tp_i, wall_s=wall,
                **{k: hist[k][:r_i, i] for k in self.metric_keys}))
        return BucketResult(results=results, rounds_run=rounds_run,
                            converged=converged, wall_s=wall,
                            interrupted=interrupted)

"""Scenario packer: bucket solo simulators by compiled-program identity.

A bucket is a set of scenarios the batched engine can serve with ONE
static-shape compilation — i.e. scenarios whose round programs are the
same trace and whose array leaves stack.  The signature below is the
exhaustive list of everything :func:`aligned.aligned_round` reads as a
Python-level static: topology shape (rows/row block/slots/overlay
family), message width, mode/fanout, liveness cadence and strike cap,
churn schedule, stagger, the kernel-path knobs (fuse_update /
pull_window / its windowed slot count), the whole fault plan (a frozen,
hashable dataclass — its values bake into the trace, and its draws are
keyed on ``(plan-seed, round, global id)``, so every scenario sharing a
plan replays the solo fault schedule bitwise), and the interpret flag.

Everything NOT in the signature is a per-scenario ARRAY the engine
batches: the topology tables (each scenario keeps the exact overlay its
solo run would build — including ``valid_w``/``deg``, so peer counts
may differ within a bucket as long as they land on the same padded row
grid), the whole simulation state (seed/PRNG chain, byzantine draw,
alive mask), and the liveness hash seed.

Power-of-two peer counts land on shared row grids (n/128 rows), which
is why the spec layer pads peer counts up to powers of two by default —
heterogeneous sweeps then collapse into few buckets instead of
singletons.

The round-14 tuning cache (tuning/resolve.signature) keys on this
signature's SHAPE — topology shape, message width, mode/fanout,
backend, statics family — but deliberately coarser: per-scenario
arrays (seeds, churn schedules, fault plans) change what a round
computes, never which schedule moves the same blocks fastest, so
scenarios in different buckets share one tuning entry.  Because the
resolved statics below (``_prefetch``/``_overlap``/``_frontier_skip``)
are read POST-resolution, a cache-substituted build routes into its
own bucket automatically — tuned and untuned scenarios never share a
compiled program unless their schedules really are identical.

The serving plane's SLOT COUNT is deliberately absent: a bucket's
width is the leading batch axis the engine vmaps over, not a static of
the per-scenario round program, which is what lets the round-17
autoscaler grow/shrink a resident bucket (migrating occupants through
the admit scatter) without ever changing where a request routes — the
signature, and therefore the affinity key the fleet router sticks to,
is width-invariant.
"""

from __future__ import annotations


def bucket_signature(sim) -> tuple:
    """Hashable identity of the compiled round program for ``sim``
    (an :class:`aligned.AlignedSimulator`).  Two sims with equal
    signatures batch into one bucket; the parity suite asserts the
    batched trajectories stay bitwise-identical to solo runs.

    Non-aligned engines that can batch (realgraph) publish their own
    identity through a ``_bucket_signature`` hook — their first element
    is the engine name, so cross-engine collisions are impossible and
    the tuple below stays the aligned family's exhaustive list."""
    fn = getattr(sim, "_bucket_signature", None)
    if fn is not None:
        return fn()
    t = sim.topo
    return (
        # --- array shapes (stacking) ---
        t.rows, t.rowblk, t.n_slots, sim.n_words,
        None if t.ytab is None else tuple(t.ytab.shape),
        # --- round-program statics ---
        sim.n_msgs, sim._n_honest, sim.mode, sim.fanout,
        sim.max_strikes, sim.liveness_every, sim.message_stagger,
        sim.fuse_update, sim.pull_window, sim._pull_slots,
        # the RESOLVED frontier block-skip flag, not the raw mode: it
        # alone decides whether the skip tables enter the trace (the
        # delta exchange never runs on the fleet's single device); the
        # resolved exchange algorithm rides next to it for the same
        # one-program-per-bucket discipline (round 16 — like _overlap,
        # it never engages on one device but keys the program family)
        sim._frontier_skip, sim._frontier_algo,
        # resolved round-10 schedule statics: the prefetch stream
        # changes the compiled kernel (scratch ring + manual DMA); the
        # overlap split never engages on the fleet's single device but
        # stays in the signature for the same one-program-per-bucket
        # discipline
        sim._prefetch, sim._overlap,
        # resolved round-11 hierarchy statics: like the overlap split,
        # the two-tier exchange never engages on the fleet's single
        # device, but the resolved factorization rides the signature
        # so a sweep mixing hier and flat scenario lines keeps the
        # one-program-per-bucket discipline
        sim.hier_hosts, sim.hier_devs, sim._hier,
        sim._liveness,
        (sim.churn.rate, sim.churn.revive, sim.churn.kill_round),
        sim.faults,            # frozen dataclass or None — hashable
        sim.interpret,
    )


def pack(sims: list, max_batch: int = 256) -> list[list[int]]:
    """Group scenario indices into buckets of signature-identical sims.

    Deterministic: buckets are ordered by first appearance and scenarios
    keep their input order inside a bucket, so a resumed sweep re-packs
    identically.  Groups larger than ``max_batch`` split into successive
    full buckets plus a remainder (the bucket-overflow path)."""
    if max_batch < 1:
        raise ValueError("max_batch must be >= 1")
    groups: dict[tuple, list[int]] = {}
    order: list[tuple] = []
    for i, sim in enumerate(sims):
        key = bucket_signature(sim)
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(i)
    buckets: list[list[int]] = []
    for key in order:
        idx = groups[key]
        for start in range(0, len(idx), max_batch):
            buckets.append(idx[start:start + max_batch])
    return buckets

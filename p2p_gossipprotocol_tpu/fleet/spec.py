"""Scenario specs: one sweep line = one NetworkConfig-expressible run.

A sweep file is JSONL — one JSON object per scenario, each key a
``network.txt`` config key (the SAME tables ``config.py`` parses, so
anything a config file can say a sweep line can say: peers, fanout,
mode, churn, byzantine fraction, fault plan, seed, ...), applied as
overrides on top of the base config the CLI was launched with.  Unknown
keys are an ERROR here — the lenient file parser's silently-ignored
unknown keys are a reference-parity behavior; a sweep typo silently
running the wrong scenario is exactly the defect class SURVEY §2-C2
exists to prevent.

Each spec resolves to the exact solo
:class:`~p2p_gossipprotocol_tpu.aligned.AlignedSimulator` the CLI would
build for that scenario (``from_config`` — same ceilings, same clamps
machinery, never silent), which is what makes the fleet's
bitwise-parity contract meaningful: the batched run serves *these*
simulators, not approximations of them.

Peer-count padding: ``pad_peers`` (the ``sweep_pad_peers`` config key,
default on) rounds each scenario's peer count UP to the next power of
two, so heterogeneous sweeps land on shared padded row grids and
collapse into few buckets (the static-shape-bucket trick the fleet
exists for).  The padding is recorded on the spec and in every results
row (``n_peers_requested`` vs ``n_peers``) — a changed scenario is
surfaced, never silent — and parity is asserted against the padded
scenario, which is the one that actually ran.
"""

from __future__ import annotations

import copy
import json
from dataclasses import dataclass, field

from p2p_gossipprotocol_tpu.config import (_REFERENCE_INT_KEYS,
                                           _SIM_FLOAT_KEYS, _SIM_INT_KEYS,
                                           _SIM_STR_KEYS, ConfigError,
                                           NetworkConfig)

#: every config-file key a sweep line may override, mapped to its
#: NetworkConfig attribute (the one source of truth is config.py's
#: parse tables — re-used here so the two surfaces cannot drift).
_KEY_TABLES = (_REFERENCE_INT_KEYS, _SIM_INT_KEYS, _SIM_FLOAT_KEYS,
               _SIM_STR_KEYS)

#: keys that name things a *scenario* cannot choose (the sweep itself,
#: the device layout, checkpointing — driver-level concerns).
_RESERVED = {"engine", "mesh_devices", "msg_shards", "sweep_file",
             "sweep_results", "sweep_max_batch", "sweep_pad_peers",
             "sweep_target", "checkpoint_every", "checkpoint_dir",
             "checkpoint_resume", "backend", "local_ip", "local_port",
             # serving plane: how the SERVER runs, never what one
             # scenario simulates (serve/scheduler.py resolves request
             # dicts through this same table; the per-REQUEST SLO
             # fields deadline_ms/priority are stripped before
             # resolution — scheduler.SLO_KEYS — so they never land
             # here)
             "serve", "serve_slots", "serve_queue_max",
             "serve_max_buckets", "serve_chunk", "serve_rounds",
             "serve_target", "serve_results", "serve_replicas",
             "serve_deadline_ms", "serve_deadline_shed",
             "serve_health_s", "serve_pipeline", "serve_inflight",
             "serve_autoscale", "serve_autoscale_min",
             "serve_autoscale_max", "serve_autoscale_hold",
             # telemetry watches the PROCESS, never one scenario
             "telemetry", "telemetry_ring", "telemetry_dump_dir"}


def _attr_for(key: str) -> str | None:
    for table in _KEY_TABLES:
        if key in table:
            return table[key]
    return None


def next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def parse_sweep_file(path: str) -> list[dict]:
    """Read a JSONL sweep file: one JSON object per line; blank lines
    and ``#`` comments skipped.  Errors carry line numbers, like the
    config parser's."""
    specs = []
    try:
        with open(path) as fp:
            lines = fp.readlines()
    except OSError as e:
        raise ConfigError(f"Unable to open sweep file: {path} ({e})")
    for ln, raw in enumerate(lines, start=1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        try:
            obj = json.loads(line)
        except ValueError as e:
            raise ConfigError(
                f"sweep file {path} line {ln}: not valid JSON ({e})")
        if not isinstance(obj, dict):
            raise ConfigError(
                f"sweep file {path} line {ln}: each line must be a "
                "JSON object of config-key overrides")
        specs.append(obj)
    if not specs:
        raise ConfigError(f"sweep file {path} holds no scenarios")
    return specs


@dataclass
class ScenarioSpec:
    """One resolved scenario: its overrides, effective config, the solo
    simulator the fleet batches, and everything the results row needs."""

    index: int
    overrides: dict
    cfg: NetworkConfig
    sim: object                       # aligned.AlignedSimulator
    n_peers: int                      # effective (possibly padded)
    n_peers_requested: int
    clamps: list[str] = field(default_factory=list)

    def row_identity(self) -> dict:
        """The spec-level fields of this scenario's results-table row."""
        out = {
            "scenario": self.index,
            "spec": self.overrides,
            "n_peers": self.n_peers,
            "n_msgs": self.sim.n_msgs,
            "mode": self.sim.mode,
            "seed": self.sim.seed,
        }
        if self.n_peers_requested != self.n_peers:
            out["n_peers_requested"] = self.n_peers_requested
        if self.clamps:
            out["clamped"] = list(self.clamps)
        # tuning provenance (round 14): which seam resolved this
        # scenario's auto statics — and, on a cache hit that changed
        # anything, exactly what was substituted.  Values are bitwise-
        # safe by the tuner's contract, so this is provenance, not a
        # different scenario.
        tuned = getattr(self.sim, "_tuning", None)
        if tuned is not None:
            out["tuned_from"] = tuned.source
            if tuned.substituted:
                out["tuned"] = {k: tuned.statics[k]
                                for k in tuned.substituted}
        return out


def apply_overrides(cfg: NetworkConfig, overrides: dict,
                    index: int) -> NetworkConfig:
    """Clone ``cfg`` and apply one sweep line's overrides, then re-run
    the config's own validation — a bad value fails with the scenario
    index, before anything is built."""
    out = copy.deepcopy(cfg)
    for key, value in overrides.items():
        attr = _attr_for(key)
        if attr is None or key in _RESERVED:
            raise ConfigError(
                f"sweep scenario {index}: unknown or reserved key "
                f"{key!r} (sweep lines override per-scenario config "
                "keys only)")
        current = getattr(out, attr)
        if isinstance(current, bool) or current is None:
            setattr(out, attr, value)
        elif isinstance(current, int) and not isinstance(value, bool):
            setattr(out, attr, int(value))
        elif isinstance(current, float):
            setattr(out, attr, float(value))
        else:
            setattr(out, attr, str(value))
    try:
        out._validate_config()
    except ConfigError as e:
        raise ConfigError(f"sweep scenario {index}: {e.message}")
    return out


def build_scenarios(base_cfg: NetworkConfig, specs: list[dict],
                    n_peers: int | None = None,
                    pad_peers: bool = True) -> list[ScenarioSpec]:
    """Resolve sweep lines to solo simulators, ready for the packer.

    ``n_peers`` (the CLI's ``--n-peers``) is the base peer count a
    scenario inherits when its line doesn't set one.  Scenarios must be
    gossip-mode (push/pull/pushpull) — the fleet batches the aligned
    engine; ``mode=sir`` and ``engine=edges`` scenarios are named
    errors, not silent substitutions.

    A scenario whose effective config carries ``graph_file`` builds a
    :class:`realgraph.RealGraphSimulator` instead (``engine`` itself is
    a reserved key — graph_file IS the realgraph request): the ingested
    graph fixes the peer count, so no power-of-two padding applies, and
    the scenario routes into its own signature bucket (the realgraph
    ``_bucket_signature`` leads with the engine name + graph
    fingerprint, so it can never collide with an aligned program)."""
    from p2p_gossipprotocol_tpu.aligned import AlignedSimulator

    out = []
    for i, overrides in enumerate(specs):
        cfg_i = apply_overrides(base_cfg, overrides, i)
        if cfg_i.mode not in ("push", "pull", "pushpull"):
            raise ConfigError(
                f"sweep scenario {i}: the fleet engine batches the "
                f"aligned gossip engine (push/pull/pushpull), not "
                f"mode={cfg_i.mode!r}")
        clamps: list[str] = []
        if cfg_i.graph_file:
            from p2p_gossipprotocol_tpu.realgraph import \
                RealGraphSimulator

            try:
                sim = RealGraphSimulator.from_config(cfg_i, clamps=clamps)
            except (ValueError, OSError) as e:
                raise ConfigError(f"sweep scenario {i}: {e}")
            n_eff = int(sim.topo.n_peers)
            out.append(ScenarioSpec(
                index=i, overrides=dict(overrides), cfg=cfg_i, sim=sim,
                n_peers=n_eff, n_peers_requested=n_eff, clamps=clamps))
            continue
        n_req = (int(overrides["n_peers"]) if "n_peers" in overrides
                 else (n_peers or cfg_i.n_peers
                       or len(cfg_i.seed_nodes)))
        n_eff = next_pow2(n_req) if pad_peers else n_req
        try:
            sim = AlignedSimulator.from_config(cfg_i, n_peers=n_eff,
                                               clamps=clamps)
        except ValueError as e:
            raise ConfigError(f"sweep scenario {i}: {e}")
        out.append(ScenarioSpec(index=i, overrides=dict(overrides),
                                cfg=cfg_i, sim=sim, n_peers=n_eff,
                                n_peers_requested=n_req, clamps=clamps))
    return out

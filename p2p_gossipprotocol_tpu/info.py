"""Peer/message data model + message identity.

Reference: info.hpp (PeerInfo + JSON serialization), peer.hpp:14-26
(Message, MessageTracker), peer.cpp:135-159 (SHA-256 identity).

Identity semantics preserved exactly: the hash covers content + timestamp +
source IP only — NOT port or msg_number (reference peer.cpp:145-147; the
SURVEY flags the same-host collision this allows, but it is observable
behavior, so the socket transport keeps it; the JAX backend uses integer
message ids and sidesteps string hashing entirely).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class PeerInfo:
    """(ip, port, last_seen); equality/hash ignore last_seen
    (reference info.hpp:11-19)."""

    ip: str
    port: int
    last_seen: float = 0.0

    def __eq__(self, other) -> bool:
        return (isinstance(other, PeerInfo)
                and self.ip == other.ip and self.port == other.port)

    def __hash__(self) -> int:
        return hash(self.ip) ^ hash(self.port)

    def to_json(self) -> dict:
        # Wire shape from info.hpp:26-32: ip, port, lastSeen (time_t secs).
        return {"ip": self.ip, "port": self.port,
                "lastSeen": int(self.last_seen)}

    @classmethod
    def from_json(cls, j: dict) -> "PeerInfo":
        return cls(j["ip"], int(j["port"]), float(j.get("lastSeen", 0)))

    def now(self) -> "PeerInfo":
        return PeerInfo(self.ip, self.port, time.time())


@dataclass
class Message:
    """Gossip message (reference peer.hpp:14-21)."""

    content: str
    timestamp: str  # epoch-ns as string (peer.cpp:361)
    source_ip: str
    source_port: int
    msg_number: int
    hash: str = ""

    def to_wire(self) -> dict:
        # Field names from peer.cpp:299-305.
        return {
            "type": "gossip",
            "content": self.content,
            "timestamp": self.timestamp,
            "source_ip": self.source_ip,
            "source_port": self.source_port,
            "msg_number": self.msg_number,
            "hash": self.hash,
        }

    @classmethod
    def from_wire(cls, j: dict) -> "Message":
        return cls(j["content"], j["timestamp"], j["source_ip"],
                   int(j["source_port"]), int(j["msg_number"]),
                   j.get("hash", ""))


def calculate_message_hash(msg: Message) -> str:
    """SHA-256 hex over content+timestamp+sourceIP (peer.cpp:141-158).

    Receivers recompute this rather than trusting the wire hash
    (peer.cpp:277) — preserved in our socket runtime.  Uses the native
    implementation (native/gossip_native.cpp — the analogue of the
    reference's OpenSSL EVP path) when built, hashlib otherwise; both
    are standard SHA-256 so identities always agree.
    """
    from p2p_gossipprotocol_tpu import native

    payload = f"{msg.content}{msg.timestamp}{msg.source_ip}".encode()
    return native.sha256(payload).hex()


@dataclass
class MessageTracker:
    """Message + the set of peers it was sent to (reference peer.hpp:23-26).

    The reference populates sent_to but never reads it (SURVEY §2-C4);
    we keep it because it makes send-exactly-once testable.
    """

    msg: Message
    sent_to: set = field(default_factory=set)

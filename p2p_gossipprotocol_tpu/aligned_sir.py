"""SIR epidemic model on the hardware-aligned overlay — the scale path
for BASELINE config 3 (the edges engine's SIRSimulator hits the same
~100k-peer gather wall as its gossip sibling; this runs the identical
compartment semantics at the aligned engine's 1M-10M-peer scale).

Semantics mirror models/sir.py:sir_round exactly:
  * infection pressure = number of transmitting (infected AND alive)
    in-neighbors, here one SUM-accumulated pallas pass over the aligned
    overlay's slots (ops/aligned_kernel.py:count_pass);
  * susceptible -> infected with p = 1 - (1-beta)^pressure;
  * infected -> recovered with probability gamma per round (dead peers
    included — recovery is biological, not network state, matching
    models/sir.py:29);
  * churn masks contacts the same way the gossip engines' does.

The reference has no epidemic model — its gossip IS the SI special case
(seen = infected, gamma = 0; peer.cpp:280-286) — so like the edges SIR
engine this consumes the ``sir_beta``/``sir_gamma`` config keys the
reference-parity config system exposes.

Every random draw (churn, infection, recovery) is keyed on the GLOBAL
row id via fold_in (aligned.row_uniform), so the sharded counterpart
(parallel/aligned_sharded.py:AlignedShardedSIRSimulator) is bitwise
equal to this engine — the same determinism contract as the gossip
pair.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from flax import struct

from p2p_gossipprotocol_tpu.aligned import (AlignedTopology,
                                            Y_REUSE_LEAK_PREFETCH,
                                            churn_rows, row_uniform)
from p2p_gossipprotocol_tpu.liveness import ChurnConfig
from p2p_gossipprotocol_tpu.ops.aligned_kernel import (LANES, count_pass,
                                                       gossip_pass,
                                                       stream_plan)


@struct.dataclass
class AlignedSIRState:
    """Compartments as two bool planes on the [rows, 128] peer grid
    (S = ~infected & ~recovered; the int8 0/1/2 compartment of
    state.py:SIRState unpacked into masks the VPU consumes directly)."""

    inf_b: jax.Array     # bool[R, 128]
    rec_b: jax.Array     # bool[R, 128]
    alive_b: jax.Array   # bool[R, 128]
    key: jax.Array
    round: jax.Array
    n_peers: int = struct.field(pytree_node=False)


def _count(mask_b: jax.Array, valid_b: jax.Array) -> jax.Array:
    return jnp.sum((mask_b & valid_b).astype(jnp.int32), dtype=jnp.int32)


@dataclass
class AlignedSIRSimulator:
    """Same surface as sim.SIRSimulator (step / run / SIRResult census,
    beta/gamma/n_seeds/churn knobs) on the aligned overlay."""

    topo: AlignedTopology
    beta: float = 0.3
    gamma: float = 0.1
    n_seeds: int = 1
    churn: ChurnConfig = None    # type: ignore[assignment]
    #: fuse the pressure count into the gossip kernel (round 10): the
    #: infectious-neighbor count rides gossip_pass's stream as its
    #: ``press`` output instead of a second full D-slot count_pass
    #: launch — on a block-perm overlay the host-side permute prep
    #: (``jnp.take(..., perm)``, the model's 3-plane term) disappears
    #: with it, one stream instead of two.  -1 auto (on for the
    #: compiled path when the overlay carries ``ytab``, off under
    #: interpret — the frontier_mode precedent), 0 = the solo
    #: count_pass (kept as the entry point for callers with no gossip
    #: stream to ride), 1 = force.  Bitwise-identical either way
    #: (tests/test_sir_fuse.py), so it is excluded from checkpoint
    #: fingerprints like fuse_update.
    sir_fuse: int = 0
    #: double-buffered DMA prefetch for the fused pass
    #: (aligned.AlignedSimulator.prefetch_depth semantics).
    prefetch_depth: int = 0
    seed: int = 0
    interpret: bool | None = None

    def __post_init__(self):
        if not 0.0 <= self.beta <= 1.0:
            raise ValueError("sir_beta must be in [0, 1]")
        if not 0.0 <= self.gamma <= 1.0:
            raise ValueError("sir_gamma must be in [0, 1]")
        if self.churn is None:
            self.churn = ChurnConfig()
        if self.interpret is None:
            self.interpret = jax.default_backend() not in ("tpu", "axon")
        if not self.interpret and (self.topo.rows < 8
                                   or self.topo.rowblk % 8):
            raise ValueError(
                f"aligned SIR on TPU needs >= 8 rows of {LANES} peers and "
                f"an 8-aligned row block (this overlay: {self.topo.rows} "
                f"rows, rowblk {self.topo.rowblk})")
        # the -1 auto rules live in tuning/resolve.py — the one
        # chokepoint every auto static resolves through (gossip-lint
        # tuning-chokepoint)
        from p2p_gossipprotocol_tpu.tuning import resolve as \
            tuning_resolve

        if self.sir_fuse not in (-1, 0, 1):
            raise ValueError("sir_fuse must be -1 (auto), 0, or 1")
        self._fuse = tuning_resolve.heuristic_sir_fuse(
            self.sir_fuse, self.interpret,
            self.topo.ytab is not None)
        if self.prefetch_depth not in (-1, 0, 2):
            raise ValueError("prefetch_depth must be -1 (auto), 0, or 2")
        self._prefetch = tuning_resolve.heuristic_prefetch(
            self.prefetch_depth, self.interpret)
        self._scan_cache: dict = {}

    # ------------------------------------------------------------------
    @classmethod
    def from_config(cls, cfg, n_peers: int | None = None,
                    n_shards: int = 1,
                    clamps: list[str] | None = None
                    ) -> "AlignedSIRSimulator":
        """Build the scale-path SIR engine from a parsed NetworkConfig —
        shared by the CLI's ``--mode sir --engine aligned`` and the
        wrapper facade (mirrors AlignedSimulator.from_config; same
        resolve_overlay clamping contract)."""
        from p2p_gossipprotocol_tpu import faults as faults_lib
        from p2p_gossipprotocol_tpu.aligned import (build_aligned,
                                                    resolve_overlay)

        plan = faults_lib.plan_from_config(cfg)
        if plan is not None and plan.engine_active():
            raise ValueError(
                "fault plans apply to the gossip modes — the SIR model "
                "has no message-transfer path to fault (use churn_rate "
                "for its peer-level failures)")
        clamps = clamps if clamps is not None else []
        n, law, n_slots = resolve_overlay(cfg, n_peers=n_peers,
                                          clamps=clamps)
        topo = build_aligned(seed=cfg.prng_seed, n=n, n_slots=n_slots,
                             degree_law=law,
                             powerlaw_alpha=cfg.powerlaw_alpha,
                             n_shards=n_shards,
                             roll_groups=cfg.roll_groups or None,
                             # honored for overlay-family parity; with
                             # sir_fuse the block-perm table also lets
                             # the fused pass delete the permute prep
                             # (the flag plane rides ytab's index maps)
                             block_perm=cfg.block_perm > 0)
        if cfg.sir_fuse == 1 and topo.ytab is None:
            clamps.append(
                "sir_fuse 1 on a row-perm overlay -> fused count only "
                "(the permute prep stays host-side without block_perm; "
                "the pass itself still fuses, bitwise-identically)")
        # The tuning chokepoint (round 14): the SIR engine's two -1
        # autos resolve like the gossip engine's — cache hit for this
        # signature wins, heuristic fallback otherwise, substitutions
        # typed into the ledger.  Both are bitwise-identical statics
        # (tests/test_sir_fuse.py, test_prefetch.py).
        from p2p_gossipprotocol_tpu.tuning import resolve as \
            tuning_resolve

        interpret = jax.default_backend() not in ("tpu", "axon")
        has_ytab = topo.ytab is not None
        sig = tuning_resolve.signature(
            rows=topo.rows, rowblk=topo.rowblk, n_slots=n_slots,
            n_words=1, mode="sir", fanout=0,
            backend="interpret" if interpret else "compiled",
            n_shards=n_shards, block_perm=has_ytab,
            roll_groups=topo.roll_groups or 0, fuse_update=0,
            pull_window=0)
        tuned = tuning_resolve.resolve_statics(
            sig,
            requested={"sir_fuse": cfg.sir_fuse,
                       "prefetch_depth": cfg.prefetch_depth},
            heuristics={
                "sir_fuse": int(tuning_resolve.heuristic_sir_fuse(
                    cfg.sir_fuse, interpret, has_ytab)),
                "prefetch_depth": tuning_resolve.heuristic_prefetch(
                    cfg.prefetch_depth, interpret)},
            legal={"sir_fuse": lambda v: v in (0, 1),
                   "prefetch_depth": lambda v: v in (0, 2)})
        sim = cls(topo=topo, beta=cfg.sir_beta, gamma=cfg.sir_gamma,
                  churn=ChurnConfig(rate=cfg.churn_rate),
                  sir_fuse=int(tuned.statics["sir_fuse"]),
                  prefetch_depth=int(tuned.statics["prefetch_depth"]),
                  seed=cfg.prng_seed)
        sim._tuning = tuned
        return sim

    # ------------------------------------------------------------------
    def init_state(self) -> AlignedSIRState:
        """Seed infections spread evenly over the peer population (the
        deterministic analogue of init_sir_state's uniform choice)."""
        topo = self.topo
        n = topo.n_peers
        n_seeds = max(1, min(self.n_seeds, n))
        pos = (np.arange(n_seeds, dtype=np.int64)
               * max(n // n_seeds, 1)) % n
        inf = np.zeros(topo.rows * LANES, bool)
        inf[pos] = True
        return AlignedSIRState(
            inf_b=jnp.asarray(inf.reshape(topo.rows, LANES)),
            rec_b=jnp.zeros((topo.rows, LANES), bool),
            alive_b=topo.valid_w != 0,
            key=jax.random.PRNGKey(self.seed),
            round=jnp.int32(0),
            n_peers=n,
        )

    # ------------------------------------------------------------------
    def traffic_model(self) -> dict:
        """Per-term analytic HBM model of one SIR round (round 10) —
        the same accounting discipline as the gossip engine's
        (aligned.AlignedSimulator.traffic_model): kernel terms replay
        the grid's DMA-descriptor sequence (stream_plan) with the
        topology's calibrated partial-reuse leak (zero on the manual
        prefetch stream, by construction); XLA-side passes are charged
        one read+write per touched plane.

        Terms: ``prep`` — the host-side permute-gather of the flag
        plane (3 planes, the gossip model's per-pass prep rule); ZERO
        on the fused path over a block-perm overlay, where the
        permutation rides the ytab index maps — the deleted second
        stream.  ``count_pass`` — the D-slot kernel walk: the flag
        plane per effective y stream, colidx + gate once, the pressure
        plane out; the fused pass adds one plane (the OR accumulator
        riding along).  Pinned closed-form in
        tests/test_traffic_model.py."""
        topo = self.topo
        R, D, C = topo.rows, topo.n_slots, LANES
        blk = topo.rowblk
        T = R // blk
        plane = R * C * 4
        fused_o = self._fuse and topo.ytab is not None
        leak = (Y_REUSE_LEAK_PREFETCH
                if self._fuse and self._prefetch else topo.reuse_leak)
        plan = stream_plan(
            np.asarray(topo.rolls), T,
            ytab=np.asarray(topo.ytab) if fused_o else None)
        eff = plan["y"] + leak * (plan["y_naive"] - plan["y"])
        kern = (eff * blk * C * 4        # flag-plane streams
                + plan["tab"] * blk * C  # colidx (int8)
                + plan["row"] * blk * C  # gate (int8)
                + plane)                 # pressure out
        if self._fuse:
            kern += plane                # the OR accumulator rides along
        terms = {"prep": 0 if fused_o else 3 * plane,
                 "count_pass": int(kern)}
        terms["total"] = sum(terms.values())
        return terms

    def hbm_bytes_per_round(self) -> int:
        """Total of :meth:`traffic_model` (bench/roofline parity with
        the gossip engine)."""
        return self.traffic_model()["total"]

    # ------------------------------------------------------------------
    def step(self, state: AlignedSIRState,
             topo: AlignedTopology | None = None
             ) -> tuple[AlignedSIRState, dict]:
        topo = self.topo if topo is None else topo
        grows = jnp.arange(topo.rows, dtype=jnp.int32)
        return aligned_sir_round(self, state, topo, grows=grows,
                                 t_off=jnp.int32(0),
                                 gather=lambda x: x, reduce=lambda x: x)

    # ------------------------------------------------------------------
    def run(self, rounds: int, state: AlignedSIRState | None = None,
            warmup: bool = False):
        """Fixed-round scan; returns the shared :class:`sim.SIRResult`.

        ``warmup=True`` executes the compiled program once untimed first
        so ``wall_s`` excludes compile + one-time program upload — the
        same benchmark-parity flag as every other run() on the scale
        path (round-2 advisor finding)."""
        import time as _time

        from p2p_gossipprotocol_tpu.sim import SIRResult

        state = self.init_state() if state is None else state
        if rounds not in self._scan_cache:
            # topo is a traced ARGUMENT, never a closure capture: a
            # captured topology is baked into the HLO as a constant,
            # and at 32M+ peers the serialized lane table alone blew
            # the remote-compile transport's body limit (HTTP 413) —
            # the gossip engine's run() passes it for the same reason
            def scanned(st, tp):
                def body(carry, _):
                    s, metrics = self.step(carry, tp)
                    return s, metrics
                return jax.lax.scan(body, st, None, length=rounds)
            self._scan_cache[rounds] = jax.jit(scanned)
        if warmup:
            w_state, _ = self._scan_cache[rounds](state, self.topo)
            int(jax.device_get(w_state.round))
        t0 = _time.perf_counter()
        state, ys = self._scan_cache[rounds](state, self.topo)
        int(jax.device_get(state.round))   # forces completion
        wall = _time.perf_counter() - t0
        return SIRResult.from_metrics(state, self.topo, ys, wall)


def aligned_sir_round(sim: AlignedSIRSimulator, state: AlignedSIRState,
                      topo: AlignedTopology, *, grows: jax.Array,
                      t_off: jax.Array, gather, reduce
                      ) -> tuple[AlignedSIRState, dict]:
    """THE SIR round, shared by the single-chip engine and
    AlignedShardedSIRSimulator — same grows/t_off/gather/reduce seams as
    aligned.aligned_round (see its docstring)."""
    valid_b = topo.valid_w != 0
    key, k_churn, k_u = jax.random.split(state.key, 3)

    alive_b = state.alive_b
    if sim.churn.rate > 0.0 or sim.churn.revive > 0.0:
        alive_b = churn_rows(k_churn, grows, alive_b, valid_b,
                             state.round, sim.churn)

    transmitting = jnp.where(state.inf_b & alive_b, jnp.int32(-1),
                             jnp.int32(0))
    if sim._fuse:
        # Fused pressure (round 10): ONE gossip_pass streams the flag
        # plane and emits the infectious-neighbor count as its press
        # output — on a block-perm overlay the permutation rides the
        # ytab index maps, so the host-side permute prep below does not
        # exist at all (one stream instead of two); bitwise-equal to
        # the solo count_pass (tests/test_sir_fuse.py).
        fused_o = topo.ytab is not None
        if fused_o:
            t_local = state.inf_b.shape[0] // topo.rowblk
            ytab_local = jax.lax.dynamic_slice(
                topo.ytab, (jnp.int32(0), jnp.int32(t_off)),
                (topo.ytab.shape[0], t_local))
            y = gather(transmitting)
        else:
            y = jnp.take(gather(transmitting), topo.perm, axis=0)
        _, pressure = gossip_pass(
            y[None], topo.colidx, topo.deg, topo.rolls + t_off,
            topo.subrolls, press=True,
            ytab=ytab_local if fused_o else None,
            prefetch_depth=sim._prefetch,
            rowblk=topo.rowblk, interpret=sim.interpret)
    else:
        y = jnp.take(gather(transmitting), topo.perm, axis=0)
        pressure = count_pass(y, topo.colidx, topo.deg,
                              topo.rolls + t_off,
                              topo.subrolls, rowblk=topo.rowblk,
                              interpret=sim.interpret)
    p_infect = 1.0 - jnp.power(jnp.float32(1.0 - sim.beta),
                               pressure.astype(jnp.float32))
    u = row_uniform(k_u, grows, (2, LANES))
    u_inf, u_rec = u[:, 0], u[:, 1]
    sus_b = ~state.inf_b & ~state.rec_b & valid_b
    new_inf = sus_b & alive_b & (u_inf < p_infect)
    recovers = state.inf_b & (u_rec < sim.gamma)
    inf_b = (state.inf_b | new_inf) & ~recovers
    rec_b = state.rec_b | recovers

    metrics = {
        "susceptible": reduce(_count(~inf_b & ~rec_b, valid_b)),
        "infected": reduce(_count(inf_b, valid_b)),
        "recovered": reduce(_count(rec_b, valid_b)),
        "new_infections": reduce(_count(new_inf, valid_b)),
        "live_peers": reduce(_count(alive_b, valid_b)),
    }
    state = AlignedSIRState(inf_b=inf_b, rec_b=rec_b, alive_b=alive_b,
                            key=key, round=state.round + 1,
                            n_peers=state.n_peers)
    return state, metrics

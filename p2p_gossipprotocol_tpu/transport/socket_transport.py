"""TCP transport speaking the reference's wire format.

The reference sends bare ``json.dump()`` bytes with NO framing and parses
whatever one 4 KB ``recv`` returns as a complete document
(peer.cpp:182-194, 256-265; seed.cpp:93-107) — which breaks the moment TCP
coalesces or fragments (SURVEY.md §2-C7).  :class:`JsonStream` stays
byte-compatible on the SEND side (identical payloads) while fixing the
receive side: it accumulates a buffer and peels off complete JSON
documents with an incremental decoder, so back-to-back reference messages
that arrive coalesced are split correctly instead of crashing the parser.
"""

from __future__ import annotations

import codecs
import json
import socket

RECV_SIZE = 4096  # reference buffer size (peer.cpp:188)
_DECODER = json.JSONDecoder()


def send_json(sock: socket.socket, obj: dict) -> None:
    """Reference-identical send: compact JSON, no frame, no newline
    (peer.cpp:182, json.dump default separators match nlohmann dump())."""
    sock.sendall(json.dumps(obj, separators=(",", ":")).encode())


def send_framed(sock: socket.socket, obj: dict) -> None:
    """Length-framed send (4-byte BE prefix) — the robust wire mode the
    reference lacks (SURVEY.md §2-C7); codec in native/gossip_native.cpp
    with a pure-Python fallback."""
    from p2p_gossipprotocol_tpu import native

    sock.sendall(native.frame_encode(
        json.dumps(obj, separators=(",", ":")).encode()))


class JsonStream:
    """Incremental JSON document splitter over a byte stream."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self._buf = ""
        # Incremental decoder: a multibyte UTF-8 character split across two
        # recv()s is held until its continuation bytes arrive, instead of
        # being mangled to U+FFFD by a per-chunk decode.
        self._decoder = codecs.getincrementaldecoder("utf-8")(
            errors="replace")

    def recv_objects(self) -> list[dict] | None:
        """Block for one recv; return parsed docs (possibly several, or
        none yet) — or None on EOF.  A recv timeout is NOT EOF: the
        connection is healthy, there is just nothing to read yet."""
        try:
            chunk = self.sock.recv(RECV_SIZE)
        except socket.timeout:
            return []
        except OSError:
            return None
        if not chunk:
            return None
        self._buf += self._decoder.decode(chunk)
        out = []
        while True:
            s = self._buf.lstrip()
            if not s:
                self._buf = ""
                break
            try:
                obj, end = _DECODER.raw_decode(s)
            except json.JSONDecodeError:
                self._buf = s  # incomplete document: wait for more bytes
                break
            out.append(obj)
            self._buf = s[end:]
        return out


class FramedStream:
    """Length-framed counterpart of :class:`JsonStream` (same
    ``recv_objects`` interface): complete frames are split off by the
    native codec; partial trailing bytes stay buffered, so TCP
    fragmentation/coalescing can never corrupt a document.

    Frame lengths are bounded by ``native.MAX_FRAME_LEN`` (16 MiB): a
    corrupt or hostile prefix — up to 4 GiB is expressible in 4 bytes —
    closes the connection immediately instead of stalling the stream
    while the buffer grows without limit (round-2 advisor finding)."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self._buf = b""

    def recv_objects(self) -> list[dict] | None:
        from p2p_gossipprotocol_tpu import native

        try:
            chunk = self.sock.recv(RECV_SIZE)
        except socket.timeout:
            return []           # no data yet ≠ EOF (see JsonStream)
        except OSError:
            return None
        if not chunk:
            return None
        self._buf += chunk
        try:
            frames, consumed = native.frame_scan(self._buf)
        except native.FrameTooLargeError:
            # Unrecoverable: the stream can never resynchronize past a
            # bogus length.  Drop the connection, surface EOF.
            self._buf = b""
            try:
                self.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self.sock.close()
            except OSError:
                pass
            return None
        self._buf = self._buf[consumed:]
        try:
            return [json.loads(f) for f in frames]
        except (json.JSONDecodeError, UnicodeDecodeError):
            # A well-framed payload that isn't JSON: the sender is
            # corrupt or hostile; drop the connection like the
            # over-length case (letting it escape would kill the reader
            # thread with a traceback instead).
            try:
                self.sock.close()
            except OSError:
                pass
            return None


WIRE_FORMATS = {
    "json": (send_json, JsonStream),      # reference byte-compatible
    "framed": (send_framed, FramedStream),
}


class SocketTransport:
    """Listening socket + connection bookkeeping for a socket-mode node.

    Mirrors the reference's listen setup: SO_REUSEADDR, backlog 10
    (peer.cpp:30-58, seed.cpp:27-55).  Deliberately NOT a
    :class:`~p2p_gossipprotocol_tpu.transport.base.Transport`: that seam
    is the simulation engine's array-movement contract (jit-traceable
    bulk primitives); this class is per-connection plumbing for the
    interop runtime in peer.py/seed.py, which moves one JSON document at
    a time over real TCP.
    """

    BACKLOG = 10

    def __init__(self, ip: str, port: int):
        self.ip = ip
        self.port = port
        self.listener: socket.socket | None = None

    def start(self) -> None:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.ip, self.port))
        s.listen(self.BACKLOG)
        self.listener = s

    def accept(self, timeout: float | None = None):
        assert self.listener is not None, "start() first"
        self.listener.settimeout(timeout)
        try:
            conn, addr = self.listener.accept()
            return conn, addr
        except (socket.timeout, OSError):
            return None, None

    @staticmethod
    def connect(ip: str, port: int, timeout: float = 2.0
                ) -> socket.socket | None:
        try:
            return socket.create_connection((ip, port), timeout=timeout)
        except OSError:
            return None

    def connect_to(self, ip: str, port: int, timeout: float = 2.0
                   ) -> socket.socket | None:
        """Instance-level connect — the seam :class:`FaultyTransport`
        overrides to inject link faults; the base class just delegates
        to the static :meth:`connect`."""
        return self.connect(ip, port, timeout)

    def stop(self) -> None:
        if self.listener is not None:
            try:
                self.listener.close()
            except OSError:
                pass
            self.listener = None


class FaultyTransport(SocketTransport):
    """Fault-injecting :class:`SocketTransport` — the socket-backend
    mirror of the engines' fault plane (faults.FaultPlan):

    * ``link_drop`` — an outbound connect is refused with this
      probability (the caller sees the same ``None`` a refused TCP
      connect yields, so the retry/backoff machinery — not special
      cases — absorbs it);
    * ``delay``     — a successful connect is held for a 10-100 ms
      jitter first (connection-setup latency).

    Send-path faults (drop/delay/duplication of individual documents)
    live in :func:`p2p_gossipprotocol_tpu.faults.wrap_send`, which
    PeerNode layers over its wire send when the plan asks for them.
    """

    def __init__(self, ip: str, port: int, plan=None, rng=None):
        super().__init__(ip, port)
        import random as _random

        self.plan = plan
        self.rng = rng or _random.Random()

    def connect_to(self, ip: str, port: int, timeout: float = 2.0
                   ) -> socket.socket | None:
        plan = self.plan
        if plan is not None:
            if plan.link_drop > 0.0 and self.rng.random() < plan.link_drop:
                return None              # the virtual wire refused us
            if plan.delay > 0.0 and self.rng.random() < plan.delay:
                import time

                time.sleep(self.rng.uniform(0.01, 0.1))
        return self.connect(ip, port, timeout)

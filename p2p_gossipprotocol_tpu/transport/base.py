"""Abstract transport interface.

The reference hard-wires BSD sockets into the gossip logic
(peer.cpp:30-58, 161-173); here delivery is pluggable — the same gossip
semantics run over TCP (interop) or over the TPU adjacency (simulation).
"""

from __future__ import annotations

import abc


class Transport(abc.ABC):
    """Delivers gossip payloads between peers."""

    @abc.abstractmethod
    def start(self) -> None:
        """Bring the transport up (bind/listen, or allocate device state)."""

    @abc.abstractmethod
    def stop(self) -> None:
        """Tear the transport down."""

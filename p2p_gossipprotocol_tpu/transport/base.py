"""Abstract transport interface.

The reference hard-wires BSD sockets into the gossip logic
(peer.cpp:30-58, 161-173); here ALL inter-peer data movement in the
simulation engine goes through this seam.  The three primitives cover
every movement the round kernels perform:

* :meth:`deliver`  — flood a transmission set over the live edge set
  (the reference's ``broadcastMessage`` loop, peer.cpp:310-312);
* :meth:`fetch`    — each peer reads one sampled neighbor's seen-set
  (the anti-entropy pull contact);
* :meth:`push_to`  — each peer writes its payload to one sampled contact
  (the push half of a push-pull exchange).

``models.gossip.make_round_fn`` takes a Transport and the Simulator
threads its own through, so swapping the implementation (see
tests/test_transport.py's dense-matmul transport) changes HOW bits move
without touching gossip semantics.
"""

from __future__ import annotations

import abc

import jax


class Transport(abc.ABC):
    """Moves gossip payloads between peers; implementations must be pure
    (jit-traceable) in the array arguments."""

    def start(self) -> None:
        """Bring the transport up (bind/listen, or allocate device state)."""

    def stop(self) -> None:
        """Tear the transport down."""

    @abc.abstractmethod
    def deliver(self, sending: jax.Array, topo,
                edge_gate: jax.Array | None = None) -> jax.Array:
        """bool[n, m] transmissions → bool[n, m] receptions over the
        edge set (optionally gated per-edge)."""

    @abc.abstractmethod
    def fetch(self, payload: jax.Array, nbr: jax.Array,
              ok: jax.Array) -> jax.Array:
        """Each peer i reads ``payload[nbr[i]]`` where ``ok[i]`` (bool[n])
        gates the contact; returns bool[n, m] of fetched bits."""

    @abc.abstractmethod
    def push_to(self, recv: jax.Array, payload: jax.Array,
                nbr: jax.Array, ok: jax.Array) -> jax.Array:
        """Each peer i with ``ok[i]`` ORs ``payload[i]`` into
        ``recv[nbr[i]]``; returns the updated recv."""

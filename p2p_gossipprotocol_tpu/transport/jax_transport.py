"""JaxTransport: message delivery as a masked OR-scatter over the
fixed-capacity adjacency in HBM — the TPU-native replacement for the
reference's per-socket ``send``/``recv`` (SURVEY.md §2 native-equivalents
table, row 1).

One ``deliver`` call moves every in-flight message across every live edge
simultaneously; there are no connections, buffers, or partial reads to
manage.  The Simulator composes this with dedup/liveness; the class exists
so transports stay swappable at the API seam.
"""

from __future__ import annotations

import jax

from p2p_gossipprotocol_tpu.graph import Topology
from p2p_gossipprotocol_tpu.ops.propagate import edge_or_scatter
from p2p_gossipprotocol_tpu.transport.base import Transport


class JaxTransport(Transport):
    def __init__(self, topo: Topology):
        self.topo = topo

    def start(self) -> None:  # nothing to bring up: state lives in HBM
        pass

    def stop(self) -> None:
        pass

    def deliver(self, sending: jax.Array,
                edge_gate: jax.Array | None = None) -> jax.Array:
        """bool[n, m] of transmissions → bool[n, m] of receptions."""
        return edge_or_scatter(sending, self.topo, edge_gate)

"""JaxTransport: inter-peer data movement as masked gathers/OR-scatters
over the fixed-capacity adjacency in HBM — the TPU-native replacement for
the reference's per-socket ``send``/``recv`` (SURVEY.md §2
native-equivalents table, row 1).

One ``deliver`` call moves every in-flight message across every live edge
simultaneously; one ``fetch``/``push_to`` pair is a whole network's worth
of anti-entropy contacts.  There are no connections, buffers, or partial
reads to manage.  The round kernels in ``models/gossip.py`` are written
against the abstract :class:`Transport`; this is the implementation the
Simulator uses by default (see tests/test_transport.py for a swapped-in
dense-matmul transport proving the seam).
"""

from __future__ import annotations

import jax

from p2p_gossipprotocol_tpu.ops.propagate import edge_or_scatter
from p2p_gossipprotocol_tpu.transport.base import Transport


class JaxTransport(Transport):
    """Stateless: the topology rides in as an argument, so one instance
    serves any graph and the methods stay jit-traceable."""

    def deliver(self, sending: jax.Array, topo,
                edge_gate: jax.Array | None = None) -> jax.Array:
        """bool[n, m] of transmissions → bool[n, m] of receptions: the
        vectorization of the reference's broadcast loop
        (peer.cpp:310-312)."""
        return edge_or_scatter(sending, topo, edge_gate)

    def fetch(self, payload: jax.Array, nbr: jax.Array,
              ok: jax.Array) -> jax.Array:
        """Each peer i reads ``payload[nbr[i]]`` where ``ok[i]`` — the
        anti-entropy pull contact (one gather)."""
        return payload[nbr] & ok[:, None]

    def push_to(self, recv: jax.Array, payload: jax.Array,
                nbr: jax.Array, ok: jax.Array) -> jax.Array:
        """Each peer i with ``ok[i]`` ORs ``payload[i]`` into
        ``recv[nbr[i]]`` — the push half of a push-pull exchange (one
        OR-scatter; scatter-max == OR over {0,1})."""
        return recv.at[nbr].max(payload & ok[:, None], mode="drop")

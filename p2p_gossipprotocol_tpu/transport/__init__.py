"""Transport layer: the seam SURVEY.md §1 prescribes between gossip
semantics and message delivery.

* :class:`Transport` — the simulation engine's array-movement contract
  (deliver / fetch / push_to); every round kernel in models/gossip.py is
  written against it.
* :class:`JaxTransport` — the default implementation: masked gathers and
  OR-scatters over the HBM adjacency (the TPU path; what Simulator uses).
* :class:`SocketTransport` + :class:`JsonStream`/:class:`FramedStream` —
  real TCP speaking the reference's unframed-JSON wire format for
  small-n interop (peer.py/seed.py plumbing, outside the array seam).
"""

from p2p_gossipprotocol_tpu.transport.base import Transport
from p2p_gossipprotocol_tpu.transport.jax_transport import JaxTransport
from p2p_gossipprotocol_tpu.transport.socket_transport import (
    FramedStream,
    JsonStream,
    SocketTransport,
    send_framed,
    send_json,
)

__all__ = ["Transport", "JaxTransport", "SocketTransport", "JsonStream",
           "FramedStream", "send_json", "send_framed"]

"""Transport layer: the seam SURVEY.md §1 prescribes between gossip
semantics and message delivery.

* :class:`JaxTransport` — delivery as masked OR-scatter over the HBM
  adjacency (the TPU path; what Simulator uses).
* :class:`SocketTransport` + :class:`JsonStream` — real TCP speaking the
  reference's unframed-JSON wire format for small-n interop.
"""

from p2p_gossipprotocol_tpu.transport.base import Transport
from p2p_gossipprotocol_tpu.transport.jax_transport import JaxTransport
from p2p_gossipprotocol_tpu.transport.socket_transport import (
    JsonStream,
    SocketTransport,
    send_json,
)

__all__ = ["Transport", "JaxTransport", "SocketTransport", "JsonStream",
           "send_json"]

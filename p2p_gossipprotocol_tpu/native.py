"""ctypes bindings for the native host runtime (native/gossip_native.cpp).

Everything here degrades gracefully: if the shared library isn't built
(`make -C native`), callers fall back to the pure-Python equivalents —
hashlib for SHA-256 (bit-identical, both are standard SHA-256) and the
numpy graph builders in graph.py.  ``available()`` reports which path is
active; nothing imports this module's hard way at package import time.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_LIB = None
_TRIED = False

# Must match gn_abi_version() in native/gossip_native.cpp.
ABI_VERSION = 2


def _lib_path() -> str:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(here, "native", "libgossip_native.so")


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    path = _lib_path()
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    # Refuse a stale build: calling a changed signature through ctypes
    # doesn't fail, it silently misbehaves (e.g. a 4-arg gn_frame_scan
    # would ignore the max_len cap entirely).  Version mismatch — or a
    # pre-versioning .so with no gn_abi_version at all — falls back to
    # the pure-Python paths, which are always current.
    try:
        lib.gn_abi_version.restype = ctypes.c_int64
        if int(lib.gn_abi_version()) != ABI_VERSION:
            return None
    except AttributeError:
        return None
    lib.gn_sha256.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                              ctypes.c_char_p]
    lib.gn_sha256.restype = None
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    lib.gn_powerlaw_edges.argtypes = [
        ctypes.c_uint64, ctypes.c_int64, ctypes.c_double, ctypes.c_int32,
        i32p, i32p, ctypes.c_int64]
    lib.gn_powerlaw_edges.restype = ctypes.c_int64
    lib.gn_er_edges.argtypes = [ctypes.c_uint64, ctypes.c_int64,
                                ctypes.c_double, i32p, i32p, ctypes.c_int64]
    lib.gn_er_edges.restype = ctypes.c_int64
    lib.gn_ba_edges.argtypes = [ctypes.c_uint64, ctypes.c_int64,
                                ctypes.c_int32, i32p, i32p, ctypes.c_int64]
    lib.gn_ba_edges.restype = ctypes.c_int64
    lib.gn_frame_encode.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                    ctypes.c_char_p, ctypes.c_uint64]
    lib.gn_frame_encode.restype = ctypes.c_int64
    lib.gn_frame_scan.argtypes = [ctypes.c_char_p, ctypes.c_uint64, i64p,
                                  ctypes.c_int64, ctypes.c_uint64]
    lib.gn_frame_scan.restype = ctypes.c_int64
    _LIB = lib
    return lib


def available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
def sha256(data: bytes) -> bytes:
    """SHA-256 digest — native when built, hashlib otherwise (identical
    output; the reference links OpenSSL for the same algorithm,
    peer.cpp:135-159)."""
    lib = _load()
    if lib is None:
        import hashlib

        return hashlib.sha256(data).digest()
    out = ctypes.create_string_buffer(32)
    lib.gn_sha256(data, len(data), out)
    return out.raw


# ---------------------------------------------------------------------------
def _run_builder(fn, cap_guess: int, *args):
    cap = cap_guess
    for _ in range(4):
        src = np.empty(cap, np.int32)
        dst = np.empty(cap, np.int32)
        n_edges = fn(*args, src, dst, cap)
        if n_edges >= 0:
            return src[:n_edges].copy(), dst[:n_edges].copy()
        cap *= 2
    raise MemoryError("native graph builder exceeded retry capacity")


def powerlaw_edges(seed: int, n: int, alpha: float = 2.5,
                   max_degree: int = 64):
    """Directed edge list under the reference's power-law fanout law
    (peer.cpp:219-222).  Returns (src, dst) int32 arrays."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library not built (make -C native)")
    cap = int(n) * int(max_degree) + 64
    return _run_builder(lib.gn_powerlaw_edges, cap, seed, n, alpha,
                        max_degree)


def er_edges(seed: int, n: int, avg_degree: float):
    lib = _load()
    if lib is None:
        raise RuntimeError("native library not built (make -C native)")
    cap = int(n * avg_degree) + int(8 * (n * avg_degree) ** 0.5) + 64
    return _run_builder(lib.gn_er_edges, cap, seed, n, avg_degree)


def ba_edges(seed: int, n: int, m: int = 4):
    lib = _load()
    if lib is None:
        raise RuntimeError("native library not built (make -C native)")
    cap = int(n) * int(m) + int(m) * int(m) + 64
    return _run_builder(lib.gn_ba_edges, cap, seed, n, m)


# ---------------------------------------------------------------------------
# A length prefix above this is a protocol violation: the prefix is
# 4 bytes, so a corrupt/hostile peer could otherwise declare up to 4 GiB
# and stall the stream while the receive buffer grows without limit
# (round-2 advisor finding).  16 MiB is ~4000× the reference's largest
# possible message (4 KB recv buffer, peer.cpp:188).
MAX_FRAME_LEN = 16 * 1024 * 1024


class FrameTooLargeError(ValueError):
    """A frame length prefix exceeded MAX_FRAME_LEN — the caller should
    drop the connection (the stream can never resynchronize)."""


def frame_encode(payload: bytes, max_len: int = MAX_FRAME_LEN) -> bytes:
    """4-byte big-endian length prefix + payload (the framing the
    reference's unframed TCP protocol lacks, SURVEY.md §2-C7)."""
    if len(payload) > max_len:
        raise FrameTooLargeError(
            f"payload of {len(payload)} bytes exceeds frame cap {max_len}")
    lib = _load()
    if lib is None:
        return len(payload).to_bytes(4, "big") + payload
    cap = len(payload) + 4
    out = ctypes.create_string_buffer(cap)
    n = lib.gn_frame_encode(payload, len(payload), out, cap)
    if n < 0:
        raise ValueError("payload too large to frame")
    return out.raw[:n]


def frame_scan(buf: bytes, max_frames: int = 1024,
               max_len: int = MAX_FRAME_LEN):
    """Complete frames in ``buf`` as (payload, end_offset) with the
    trailing partial bytes left to the caller's buffer.  Raises
    :class:`FrameTooLargeError` the moment any length prefix exceeds
    ``max_len`` — before buffering a single payload byte for it."""
    lib = _load()
    if lib is None:
        frames = []
        pos = 0
        while pos + 4 <= len(buf) and len(frames) < max_frames:
            flen = int.from_bytes(buf[pos:pos + 4], "big")
            if flen > max_len:
                raise FrameTooLargeError(
                    f"frame prefix declares {flen} bytes (cap {max_len})")
            if pos + 4 + flen > len(buf):
                break
            frames.append(buf[pos + 4:pos + 4 + flen])
            pos += 4 + flen
        return frames, pos
    spans = np.empty(2 * max_frames, np.int64)
    count = int(lib.gn_frame_scan(buf, len(buf), spans, max_frames,
                                  max_len))
    if count < 0:
        raise FrameTooLargeError(
            f"frame prefix exceeds cap {max_len} bytes")
    frames = []
    pos = 0
    for i in range(count):
        off, flen = int(spans[2 * i]), int(spans[2 * i + 1])
        frames.append(buf[off:off + flen])
        pos = off + flen
    return frames, pos

"""Closed-loop autotuner (docs/ARCHITECTURE.md "The tuning seam").

* :mod:`~p2p_gossipprotocol_tpu.tuning.resolve` — THE chokepoint every
  ``-1``-auto performance static resolves through: explicit value >
  cache hit (bitwise-safe statics only) > the registered open-coded
  heuristic, each substitution a typed ``tuned`` ledger event;
* :mod:`~p2p_gossipprotocol_tpu.tuning.cache` — the persisted tuning
  cache, keyed like the fleet packer's bucket signature, written with
  the checkpoint plane's atomic + CRC + schema discipline
  (``GOSSIP_TUNING_CACHE`` env; ``off`` disables — zero config knobs);
* :mod:`~p2p_gossipprotocol_tpu.tuning.search` — the offline sweep:
  enumerate the LEGAL static space (the engines' own clamp rules gate
  candidates), time short calibrated runs, persist the winner
  (``python -m p2p_gossipprotocol_tpu.tuning`` / ``make tune``);
* online: the telemetry roofline's drift gauge marks a signature stale
  (``retune_requested``) and the watchdog's tune step re-sweeps it.

Hard contract (ROADMAP item 5): tuned values are statics from the
bitwise-identical family only, so tuned runs equal untuned runs
bit-for-bit; tuned >= hand-picked defaults on every landed bench row;
zero new config knobs.

``resolve``/``cache`` are stdlib-only (no jax) so the telemetry plane
may import them; ``search`` drives real engines and is CLI-side.
"""

from p2p_gossipprotocol_tpu.tuning import cache, resolve  # noqa: F401

__all__ = ["cache", "resolve"]

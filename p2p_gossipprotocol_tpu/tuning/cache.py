"""Persisted tuning cache: signature -> measured-best performance statics.

One JSON file maps tuning signatures (:func:`resolve.signature` — the
fleet packer's bucket-signature *shape*: topology shape, message width,
mode/fanout, backend, statics family) to the statics the offline sweep
(:mod:`tuning.search`) measured best for that shape, with the
checkpoint plane's artifact discipline applied to a host-side cache:

* **atomic writes** — every mutation rewrites the whole file via
  ``utils.logging.write_atomic`` (tmp + fsync + rename), so a reader
  never sees a torn cache;
* **per-entry CRC32** — each entry carries a CRC over its canonical
  JSON form; a mismatch names the entry and the resolver falls back to
  the heuristic for that signature instead of trusting half-written
  values;
* **schema pin** — a cache written by a newer build is a named
  :class:`StaleTuningSchema`, never a misread;
* **named errors, never a crash** — every defect class
  (:class:`CorruptTuningCache` for torn/unreadable files and CRC
  mismatches, :class:`StaleTuningSchema` for schema drift) derives from
  :class:`TuningCacheError`; :func:`lookup` catches them all, emits one
  typed ``tuning_cache_error`` ledger event, and answers None — the
  heuristic fallback — because a corrupt *cache* must never take down a
  *run* (the cache only ever chooses between bitwise-identical
  schedules).

Location: the ``GOSSIP_TUNING_CACHE`` environment variable only — the
tuner adds ZERO config keys (the ROADMAP item-5 contract).  Unset, the
cache lives at ``benchmarks/results/tuning_cache.json`` in the repo
(where ``measure_round14`` commits the landed CPU entries);
``GOSSIP_TUNING_CACHE=off`` disables lookups entirely (the A/B
drivers' default arm, and the escape hatch).

This module is stdlib-only (no jax) so the telemetry plane's roofline
tracker can mark signatures stale without violating its
zero-device-computation contract.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib

SCHEMA_VERSION = 1
ENV_CACHE = "GOSSIP_TUNING_CACHE"
_OFF = ("off", "0", "none", "disabled")

#: default cache location (repo-relative): the committed artifact the
#: watchdog's measure_round14 step refreshes.
DEFAULT_CACHE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "benchmarks", "results",
    "tuning_cache.json")


class TuningCacheError(Exception):
    """Base of every named tuning-cache defect — callers that must not
    crash catch exactly this (:func:`lookup` does, answering None)."""


class CorruptTuningCache(TuningCacheError):
    """Torn/unreadable cache file, or a CRC mismatch naming the bad
    entry."""


class StaleTuningSchema(TuningCacheError):
    """Cache schema newer than this build understands."""


def cache_path() -> str | None:
    """The active cache file, or None when tuning is disabled
    (``GOSSIP_TUNING_CACHE=off``)."""
    raw = os.environ.get(ENV_CACHE)
    if raw is None:
        return DEFAULT_CACHE
    raw = raw.strip()
    if not raw or raw.lower() in _OFF:
        return None
    return raw


def sig_key(sig: tuple) -> str:
    """Stable string form of a tuning signature (the JSON map key)."""
    return "|".join(str(s) for s in sig)


def _entry_crc(entry: dict) -> int:
    """CRC32 over the entry's canonical JSON form, ``crc32`` excluded."""
    body = {k: v for k, v in entry.items() if k != "crc32"}
    return zlib.crc32(json.dumps(body, sort_keys=True).encode()) \
        & 0xFFFFFFFF


# one read-modify-write at a time per process; cross-process safety
# comes from the atomic rename (last writer wins, readers never torn)
_LOCK = threading.RLock()

# memoized parse keyed by (path, mtime, size) — resolve consults the
# cache once per simulator build, and a sweep builds hundreds
_MEMO: dict = {}


def load(path: str | None = None) -> dict:
    """Parse + verify the cache; returns ``{sig_key: entry}`` (empty
    when the file does not exist).  Raises the NAMED defect:
    :class:`CorruptTuningCache` for an unparseable/torn file or a CRC
    mismatch (naming the entry), :class:`StaleTuningSchema` for a
    newer schema."""
    path = path or cache_path()
    if path is None or not os.path.exists(path):
        return {}
    try:
        with open(path) as fp:
            doc = json.load(fp)
    except (OSError, ValueError) as e:
        raise CorruptTuningCache(
            f"tuning cache {path} is torn or unreadable "
            f"({type(e).__name__}: {e})") from e
    if not isinstance(doc, dict) or "entries" not in doc:
        raise CorruptTuningCache(
            f"tuning cache {path} has no entries block "
            "(not a tuning cache?)")
    if int(doc.get("schema", 0)) > SCHEMA_VERSION:
        raise StaleTuningSchema(
            f"tuning cache {path} schema {doc.get('schema')} is newer "
            f"than this build's {SCHEMA_VERSION} — upgrade, or retune "
            "with this build")
    entries = doc["entries"]
    for key, entry in entries.items():
        if _entry_crc(entry) != int(entry.get("crc32", -1)):
            raise CorruptTuningCache(
                f"tuning cache {path}: CRC mismatch in entry {key!r} "
                "— the entry cannot be trusted (retune, or delete the "
                "cache)")
    return entries


def _load_memo(path: str) -> dict:
    try:
        st = os.stat(path)
        stamp = (st.st_mtime_ns, st.st_size)
    except OSError:
        stamp = None
    with _LOCK:
        hit = _MEMO.get(path)
        if hit is not None and hit[0] == stamp:
            return hit[1]
    entries = load(path)        # may raise — caller classifies
    with _LOCK:
        _MEMO[path] = (stamp, entries)
    return entries


def lookup(sig: tuple, path: str | None = None) -> dict | None:
    """The resolver's read: the entry for ``sig`` — or None on a miss,
    a stale-marked entry (drift requested a retune; the heuristic rules
    serve until the next sweep lands), a disabled cache, or ANY cache
    defect (named error recorded as one typed ``tuning_cache_error``
    ledger event; the run proceeds on the heuristics — never a
    crash)."""
    path = path or cache_path()
    if path is None:
        return None
    try:
        entries = _load_memo(path)
    except TuningCacheError as e:
        from p2p_gossipprotocol_tpu.telemetry.recorder import recorder

        recorder().event("tuning_cache_error",
                         error=type(e).__name__, detail=str(e))
        return None
    entry = entries.get(sig_key(sig))
    if entry is None or entry.get("stale"):
        return None
    return entry


def _rewrite(path: str, entries: dict) -> None:
    from p2p_gossipprotocol_tpu.utils.logging import write_atomic

    doc = {"schema": SCHEMA_VERSION,
           "written_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
           "entries": entries}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    write_atomic(path, json.dumps(doc, sort_keys=True, indent=1) + "\n")
    with _LOCK:
        _MEMO.pop(path, None)


def store(sig: tuple, statics: dict, *, ms_per_round: float,
          default_ms_per_round: float, note: dict | None = None,
          path: str | None = None) -> dict:
    """Write/replace the entry for ``sig`` (read-modify-write under the
    atomic-rename discipline).  A pre-existing corrupt cache is
    replaced wholesale — the sweep's fresh measurements are the
    recovery path the corruption runbook names."""
    path = path or cache_path()
    if path is None:
        raise TuningCacheError(
            "tuning cache is disabled (GOSSIP_TUNING_CACHE=off) — "
            "nowhere to store the sweep result")
    with _LOCK:   # serialize in-process writers; rename wins across
        try:
            entries = load(path)
        except TuningCacheError:
            entries = {}
        entry = {
            "signature": list(sig),
            "statics": dict(statics),
            "ms_per_round": round(float(ms_per_round), 6),
            "default_ms_per_round":
                round(float(default_ms_per_round), 6),
            "tuned_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "stale": False,
        }
        if note:
            entry["note"] = dict(note)
        entry["crc32"] = _entry_crc(entry)
        entries[sig_key(sig)] = entry
        _rewrite(path, entries)
        return entry


def mark_stale(sig: tuple, path: str | None = None) -> bool:
    """Flag the entry for ``sig`` stale (the drift gauge's retune
    request): lookups skip it until the next sweep rewrites it.
    Returns whether an entry was marked.  Never raises — this runs on
    the telemetry plane's chunk path."""
    try:
        path = path or cache_path()
        if path is None:
            return False
        with _LOCK:
            entries = load(path)
            entry = entries.get(sig_key(sig))
            if entry is None or entry.get("stale"):
                return False
            entry["stale"] = True
            entry["stale_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
            entry["crc32"] = _entry_crc(entry)
            _rewrite(path, entries)
            return True
    except (TuningCacheError, OSError):
        return False


def stale_signatures(path: str | None = None) -> list[str]:
    """Signature keys currently marked stale (the retune work list the
    watchdog's tune step cashes)."""
    try:
        return sorted(k for k, e in load(path).items()
                      if e.get("stale"))
    except TuningCacheError:
        return []

"""THE resolver chokepoint for ``-1``-auto performance statics.

Every place the repo used to open-code a "-1 means auto, pick the rule"
decision (``aligned.AlignedSimulator.from_config`` / ``__post_init__``,
``aligned_sir``, the serving loop's ``serve_chunk``) now resolves
through this module, so the closed-loop autotuner has ONE seam to
substitute measured-best values through — and gossip-lint's
``tuning-chokepoint`` rule keeps it that way (an auto-sentinel test on
a known static outside this file is a finding).

Resolution order, per static:

1. an EXPLICIT configured value (anything but the auto sentinel) is
   honored unconditionally — the tuner never overrides a human;
2. a cache hit (:mod:`tuning.cache`, keyed by :func:`signature`) wins
   over the heuristic — but only for the statics in :data:`TUNABLE`,
   the family proven **bitwise-identical** across values by the repo's
   parity suites (frontier/prefetch/overlap/hier/sir_fuse pick HOW the
   same blocks move, never what a round computes; ``serve_chunk`` only
   paces admission boundaries, and every served scenario is bitwise its
   solo run at any chunk).  Values that fail the caller's legality
   check are rejected with a typed ``tuning_rejected`` event and fall
   through;
3. the registered HEURISTIC — the exact open-coded rule that shipped
   before the tuner existed (kept here verbatim so the untuned path
   cannot drift).

Every cache substitution is recorded as one typed ``tuned`` telemetry
event (always-on ledger) and in the returned :class:`Resolved` record,
which rides the built simulator as ``sim._tuning`` — bench rows,
fleet/serve result rows, and the live roofline read provenance from it.

Deliberately NOT tunable: ``block_perm``, ``rowblk``, ``roll_groups``,
``pull_window``, ``fuse_update``.  Those statics shape the overlay (a
different row-block grid draws different block rolls) or the VMEM
budget that shapes it, so substituting them would change the
trajectory — the tuner's hard contract is bitwise-identical results.
Their heuristics still live here (the chokepoint centralizes every
auto rule), they are recorded in the SIGNATURE instead (a family
component), and the search space documents them as
identity-changing (docs/PERFORMANCE.md "Round 14").

stdlib-only (no jax): the telemetry roofline tracker computes
signatures on its chunk path under the zero-device-computation
contract.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from p2p_gossipprotocol_tpu.tuning import cache as tuning_cache

#: cache-substitutable statics — ONLY the bitwise-safe family (see
#: module docstring; the parity tests behind each: test_frontier.py,
#: test_prefetch.py, test_overlap.py, test_hier.py, test_sir_fuse.py,
#: test_serve.py, test_tuning.py).
TUNABLE = ("frontier_mode", "frontier_threshold", "frontier_algo",
           "prefetch_depth", "overlap_mode", "hier_mode", "sir_fuse",
           "serve_chunk",
           # the realgraph family (round 19): pack width reshapes the
           # degree-bucket tables and scatter picks gather-vs-scatter
           # delivery — both pick HOW the same boolean OR is computed,
           # never what a round delivers (tests/test_realgraph.py pins
           # the bitwise side), so both are cache-substitutable
           "realgraph_pack_width", "realgraph_scatter")

#: signature schema tag — bump when the tuple layout changes so old
#: cache entries miss instead of misresolving.
SIG_VERSION = "tune-v1"

#: the serving loop's default admission-boundary cadence (rounds per
#: chunk) — the value ``config.serve_chunk`` shipped with before it
#: grew the -1 auto spelling.
SERVE_CHUNK_DEFAULT = 8

#: the frontier delta-exchange capacity default, as a fraction of each
#: shard's packed words (aligned.FRONTIER_THRESHOLD_DEFAULT re-exports
#: this value; the derivation lives there).
FRONTIER_THRESHOLD_DEFAULT = 1.0 / 64.0


# ---------------------------------------------------------------------
# Registered heuristic fallbacks — the open-coded rules, verbatim.

def heuristic_on(requested: int, interpret: bool) -> bool:
    """The shared auto rule for the 0/1 schedule knobs (frontier_mode,
    frontier_algo, overlap_mode, hier_mode): -1 = on for the compiled
    path, off under interpret (the round-6/8/10 inversion precedent);
    0/1 force."""
    return requested == 1 or (requested == -1 and not interpret)


def heuristic_prefetch(requested: int, interpret: bool) -> int:
    """prefetch_depth auto rule: the manual double-buffered stream (2)
    on the compiled path, the BlockSpec pipeline (0) under interpret."""
    if requested == 2 or (requested == -1 and not interpret):
        return 2
    return 0


def heuristic_sir_fuse(requested: int, interpret: bool,
                       has_ytab: bool) -> bool:
    """sir_fuse auto rule: fuse on the compiled path when the overlay
    carries the block-perm index table (the permute prep only vanishes
    with ytab)."""
    return (requested == 1
            or (requested == -1 and not interpret and has_ytab))


def heuristic_block_perm(requested: int, n_words: int, mode: str,
                         n_slots: int, roll_groups: int | None,
                         min_words: int = 4) -> bool:
    """from_config's fused-overlay AUTO rule (round 6, measured: -43%
    ms/round at W=8, a wash at W=1 — ``min_words`` is
    aligned.AUTO_BLOCK_PERM_MIN_WORDS).  NOT cache-tunable: the
    block-granular permutation draws a different overlay, so a
    substitution would change the trajectory — it enters the tuning
    SIGNATURE instead."""
    if requested < 0:
        return (n_words >= min_words and mode != "pull"
                and n_slots >= 2
                and (roll_groups is None or roll_groups >= 2))
    return bool(requested)


def heuristic_rowblk(n_words: int, budget: int, cap: int) -> int:
    """The VMEM-budget row-block rule (round 6: wide blocks at small W
    — ``budget``/``cap`` are aligned.MAX_WORDS_X_ROWBLK (halved under
    fuse_update) and aligned.MAX_CONFIG_ROWBLK).  NOT cache-tunable:
    the row-block grid shapes the block-roll neighbor map."""
    return min(cap, max(8, budget // n_words // 8 * 8))


def heuristic_frontier_threshold(requested: float) -> float:
    """frontier_threshold auto rule: -1 = the 1/64 capacity default
    (aligned.FRONTIER_THRESHOLD_DEFAULT has the derivation)."""
    return (FRONTIER_THRESHOLD_DEFAULT if requested == -1
            else float(requested))


def heuristic_serve_chunk(requested: int) -> int:
    """serve_chunk auto rule: -1 = the 8-round admission cadence the
    serving plane shipped with."""
    return SERVE_CHUNK_DEFAULT if requested == -1 else int(requested)


#: realgraph_pack_width's auto value (realgraph/pack.py derives it:
#: wide enough for >99% of power-law vertices in one row, narrow
#: enough that a hub can't widen everyone's lane)
REALGRAPH_PACK_WIDTH_DEFAULT = 256


def heuristic_realgraph_pack_width(requested: int) -> int:
    """realgraph_pack_width auto rule: -1 = the 256-slot degree-bucket
    cap the realgraph engine shipped with (hubs beyond it split into
    multiple rows — semantics-free under the boolean OR)."""
    return (REALGRAPH_PACK_WIDTH_DEFAULT if requested == -1
            else int(requested))


def heuristic_realgraph_scatter(requested: int,
                                dst_static: bool) -> int:
    """realgraph_scatter auto rule: the packed gather (0) whenever the
    overlay's ``dst`` is static (the gather tables pre-resolve edge
    ids, so rewiring would stale them — realgraph.engine.dst_is_static
    is the predicate), the inherited edge scatter (1) otherwise."""
    if requested in (0, 1):
        return int(requested)
    return 0 if dst_static else 1


# ---------------------------------------------------------------------
# Signatures.

def signature(*, rows: int, rowblk: int, n_slots: int, n_words: int,
              mode: str, fanout: int, backend: str, n_shards: int,
              block_perm: bool, roll_groups: int, fuse_update: int,
              pull_window: int, hier: tuple = (0, 0)) -> tuple:
    """The tuning cache key: the fleet packer's bucket-signature SHAPE
    — topology shape (rows x rowblk x slots), message width, mode and
    fanout, backend (compiled vs interpret — the round-6/8/10
    inversions make these different regimes), shard count, and the
    statics FAMILY (overlay family + the identity-changing statics the
    tuner must not substitute).  Narrower than the packer's signature
    on purpose: per-scenario arrays (seeds, churn schedules, fault
    plans) don't change which schedule is fastest, so scenarios that
    pack into different buckets still share one tuning entry."""
    return (SIG_VERSION, int(rows), int(rowblk), int(n_slots),
            int(n_words), str(mode), int(fanout), str(backend),
            int(n_shards), bool(block_perm), int(roll_groups),
            int(fuse_update), int(pull_window),
            int(hier[0]), int(hier[1]))


def signature_for_sim(sim) -> tuple:
    """The signature of an already-built simulator (sharded wrappers
    expose their solo core as ``_inner``; plain attribute reads only —
    safe on the telemetry plane)."""
    inner = getattr(sim, "_inner", sim)
    topo = inner.topo
    return signature(
        rows=topo.rows, rowblk=topo.rowblk, n_slots=topo.n_slots,
        n_words=int(getattr(inner, "n_words", 1) or 1),
        mode=str(getattr(inner, "mode", "sir")),
        fanout=int(getattr(inner, "fanout", 0) or 0),
        backend="interpret" if inner.interpret else "compiled",
        n_shards=int(getattr(sim, "n_shards", 1) or 1),
        block_perm=topo.ytab is not None,
        roll_groups=int(topo.roll_groups or 0),
        fuse_update=int(bool(getattr(inner, "fuse_update", 0))),
        pull_window=int(bool(getattr(inner, "pull_window", 0))),
        hier=(int(getattr(inner, "hier_hosts", 0) or 0),
              int(getattr(inner, "hier_devs", 0) or 0)))


def realgraph_signature(*, n_peers: int, edge_capacity: int, mode: str,
                        fanout: int, backend: str) -> tuple:
    """The realgraph family's tuning cache key: graph SHAPE (vertex
    count x padded edge capacity — the statics the packed tables'
    program shapes derive from), mode/fanout, backend.  Deliberately
    NOT the graph's content fingerprint: two same-shape graphs share
    one best pack width, and per-graph entries would make the cache
    miss on every fresh ingest."""
    return (SIG_VERSION, "realgraph", int(n_peers),
            int(edge_capacity), str(mode), int(fanout), str(backend))


def serve_signature(slots: int, rounds: int) -> tuple:
    """serve_chunk's cache key: the serving loop paces ALL resident
    buckets with one chunk length, so the key is the loop's own shape
    (slot width x per-scenario round budget), not any one scenario's."""
    return (SIG_VERSION, "serve", int(slots), int(rounds))


# ---------------------------------------------------------------------
# The chokepoint.

@dataclass
class Resolved:
    """One build's resolution record (rides the simulator as
    ``sim._tuning``): the signature, every resolved static, and the
    provenance bench/fleet/serve rows report as ``tuned_from``."""

    signature: tuple
    statics: dict
    source: str                      # "cache" | "heuristic"
    substituted: tuple = ()          # statics the cache overrode
    heuristics: dict = field(default_factory=dict)


def resolve_statics(sig: tuple, requested: dict, heuristics: dict,
                    legal: dict | None = None) -> Resolved:
    """Resolve every static in ``requested`` (name -> configured
    value; -1 is the auto sentinel for every tunable static) against
    the cache entry for ``sig``, falling back to ``heuristics`` (name
    -> the open-coded rule's value).  ``legal`` maps a name to a
    predicate a cache value must pass (the engine's own clamp rules —
    an illegal cached value is rejected+recorded, never applied).

    Explicit values always win; cache values substitute only for
    statics still at their auto sentinel AND listed in
    :data:`TUNABLE`."""
    from p2p_gossipprotocol_tpu.telemetry.recorder import recorder

    entry = tuning_cache.lookup(sig)
    cached = (entry or {}).get("statics", {})
    out: dict = {}
    subbed: list = []
    used_cache = False
    for name, req in requested.items():
        if req != -1:                       # explicit: always honored
            out[name] = req
            continue
        val = heuristics[name]
        if name in TUNABLE and name in cached:
            cand = cached[name]
            ok = legal.get(name, _always)(cand) if legal else True
            if ok:
                used_cache = True
                out[name] = cand
                if cand != val:
                    subbed.append(name)
                    recorder().event(
                        "tuned", static=name, value=cand,
                        heuristic=val,
                        signature=tuning_cache.sig_key(sig))
                continue
            recorder().event(
                "tuning_rejected", static=name, value=cand,
                signature=tuning_cache.sig_key(sig),
                detail="cached value fails this build's legality "
                       "rules — heuristic used")
        out[name] = val
    return Resolved(signature=sig, statics=out,
                    source="cache" if used_cache else "heuristic",
                    substituted=tuple(subbed),
                    heuristics=dict(heuristics))


def _always(_v) -> bool:
    return True


def resolve_serve_chunk(requested: int, *, slots: int,
                        rounds: int) -> tuple[int, str]:
    """The serving loop's chunk cadence through the chokepoint:
    ``(resolved_chunk, tuned_from)``.  -1 = auto (cache hit or the
    8-round default); explicit values are honored."""
    res = resolve_statics(
        serve_signature(slots, rounds),
        requested={"serve_chunk": int(requested)},
        heuristics={"serve_chunk": SERVE_CHUNK_DEFAULT},
        legal={"serve_chunk": lambda v: isinstance(v, int)
               and not isinstance(v, bool) and v >= 1})
    return int(res.statics["serve_chunk"]), res.source

"""``python -m p2p_gossipprotocol_tpu.tuning`` — the offline sweep CLI.

    python -m p2p_gossipprotocol_tpu.tuning network.txt \
        [--n-peers N] [--rounds R] [--repeats K] [--cache PATH] \
        [--force] [--serve] [--stale]

Sweeps the legal static space for the config (tuning/search.py), times
candidates with short calibrated runs, and persists the winner in the
tuning cache (``--cache`` > ``GOSSIP_TUNING_CACHE`` > the repo
default).  ``--force`` re-sweeps a signature that is already cached;
``--serve`` also sweeps the serving loop's ``serve_chunk`` cadence;
``--stale`` lists signatures the drift gauge has marked for retune
(the watchdog's tune step re-sweeps its configured shapes, which
rewrites them).  Exit 0 on a stored (or already-fresh) entry.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m p2p_gossipprotocol_tpu.tuning",
        description="offline autotuner sweep (docs/PERFORMANCE.md "
                    "'Round 14')")
    ap.add_argument("config", help="network.txt-format config file")
    ap.add_argument("--n-peers", type=int, default=None)
    ap.add_argument("--rounds", type=int, default=8,
                    help="rounds per timed candidate scan (default 8)")
    ap.add_argument("--repeats", type=int, default=2,
                    help="timed scans per candidate; min wins "
                         "(default 2)")
    ap.add_argument("--cache", default=None,
                    help="cache file (default GOSSIP_TUNING_CACHE, "
                         "then benchmarks/results/tuning_cache.json)")
    ap.add_argument("--force", action="store_true",
                    help="re-sweep even if the signature is cached")
    ap.add_argument("--serve", action="store_true",
                    help="also sweep the serving loop's serve_chunk")
    ap.add_argument("--engine", default=None,
                    help="override the config's engine (the tuner "
                         "needs the aligned family; a config built "
                         "for engine=edges tunes nothing)")
    ap.add_argument("--stale", action="store_true",
                    help="list stale-marked signatures and exit")
    args = ap.parse_args(argv)

    from p2p_gossipprotocol_tpu.tuning import cache as tuning_cache

    if args.stale:
        for key in tuning_cache.stale_signatures(args.cache):
            print(key)
        return 0

    from p2p_gossipprotocol_tpu.config import ConfigError, NetworkConfig

    try:
        cfg = NetworkConfig(args.config)
    except ConfigError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if args.engine:
        cfg.engine = args.engine
    elif cfg.engine == "edges":
        # the scale path is what has statics to tune; say so rather
        # than dying on a stock reference config
        print("[tune] config says engine=edges (no tunable statics) "
              "— tuning the aligned scale path instead; pass "
              "--engine edges to refuse", file=sys.stderr)
        cfg.engine = "aligned"
    elif cfg.engine == "realgraph":
        # realgraph's statics (realgraph_pack_width/realgraph_scatter)
        # resolve through the tuning chokepoint + cache at build time
        # — the timed sweep below drives the aligned family only
        print("[tune] config says engine=realgraph — its statics "
              "(realgraph_pack_width/realgraph_scatter) resolve "
              "through the tuning chokepoint at build time; the "
              "timed sweep tunes the aligned scale path instead; "
              "pass --engine realgraph to refuse", file=sys.stderr)
        cfg.engine = "aligned"

    from p2p_gossipprotocol_tpu.engines import probe_backend
    from p2p_gossipprotocol_tpu.tuning import search

    probe_backend()
    entry = search.tune_config(
        cfg, n_peers=args.n_peers, rounds=args.rounds,
        repeats=args.repeats, path=args.cache, force=args.force,
        log=lambda *a: print(*a, file=sys.stderr))
    if args.serve:
        search.tune_serve_chunk(
            cfg, path=args.cache,
            log=lambda *a: print(*a, file=sys.stderr))
    print(json.dumps(entry, indent=1, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Offline sweep autotuner: time the legal static space, persist the
winner.

Given a parsed NetworkConfig, :func:`tune_config` builds the DEFAULT
simulator (tuning cache disabled — the heuristics' own pick), derives
its tuning signature, enumerates the candidate space over the
bitwise-safe statics (:data:`resolve.TUNABLE`), and times each
candidate with short calibrated runs — one warm-up execution to
exclude compile/upload (the bench.py timing discipline), then the
minimum ``wall_s`` over ``repeats`` fixed-round scans.

Legality is the ENGINES' OWN clamp machinery, not a re-implementation:
every candidate builds through ``engines.build_simulator`` with the
statics forced explicitly, and a build whose clamp ledger names the
forced knob (or that raises) is skipped — an illegal combination is
never timed (the combinatorics shrink to what can actually run).
Candidates that resolve to the same effective schedule as one already
timed are deduplicated on their resolved fields.

The DEFAULT pick is always a candidate and wins ties: the stored entry
is strictly ``tuned <= default`` by construction, with a 2% noise
guard (a "win" inside measurement noise stores the default — a cache
must never encode jitter as a schedule preference), which is what
makes ``measure_round14``'s ``tuned_ge_default`` acceptance hold on
every row.

The search only runs statics from the bitwise-identical family, so
every timed candidate computes the exact same trajectory — the sweep
is a pure schedule race (docs/PERFORMANCE.md "Round 14" has the
search-space table).
"""

from __future__ import annotations

import copy
import itertools
import os
import time

from p2p_gossipprotocol_tpu.tuning import cache as tuning_cache
from p2p_gossipprotocol_tpu.tuning import resolve as tuning_resolve

#: candidate values per tunable static; gated per-config by
#: :func:`candidate_space` and then by the engines' clamp rules.
CANDIDATES = {
    "frontier_mode": (0, 1),
    "prefetch_depth": (0, 2),
    "overlap_mode": (0, 1),
    "hier_mode": (0, 1),
    "sir_fuse": (0, 1),
    "frontier_threshold": (1.0 / 128, 1.0 / 64, 1.0 / 32, 1.0 / 16),
}

#: a candidate must beat the default by more than this fraction to be
#: stored — anything inside the band is measurement noise.
NOISE_FRAC = 0.02


class _cache_disabled:
    """Context: GOSSIP_TUNING_CACHE=off, restored on exit (the default
    arm must resolve by heuristics whatever the environment says)."""

    def __enter__(self):
        self._prev = os.environ.get(tuning_cache.ENV_CACHE)
        os.environ[tuning_cache.ENV_CACHE] = "off"
        return self

    def __exit__(self, *exc):
        if self._prev is None:
            os.environ.pop(tuning_cache.ENV_CACHE, None)
        else:
            os.environ[tuning_cache.ENV_CACHE] = self._prev
        return False


def candidate_space(sim, cfg) -> dict:
    """The per-config slice of :data:`CANDIDATES`: statics that cannot
    engage for this config (overlap without a block-perm overlay, hier
    without a factorized mesh, threshold without a sharded exchange)
    are excluded up front; what remains still builds through the
    engines' clamp rules before it is timed."""
    inner = getattr(sim, "_inner", sim)
    n_shards = int(getattr(sim, "n_shards", 1) or 1)
    space: dict = {}
    if cfg.mode == "sir":
        space["sir_fuse"] = CANDIDATES["sir_fuse"]
        space["prefetch_depth"] = CANDIDATES["prefetch_depth"]
        return space
    space["frontier_mode"] = CANDIDATES["frontier_mode"]
    space["prefetch_depth"] = CANDIDATES["prefetch_depth"]
    if n_shards > 1:
        space["frontier_threshold"] = CANDIDATES["frontier_threshold"]
        if inner.topo.ytab is not None and cfg.mode != "pull":
            space["overlap_mode"] = CANDIDATES["overlap_mode"]
        if getattr(inner, "hier_hosts", 0) > 1:
            space["hier_mode"] = CANDIDATES["hier_mode"]
    return space


def _resolved_key(sim, names) -> tuple:
    """The candidate's EFFECTIVE schedule — dedup key, read off the
    built simulator's resolved fields so two config spellings that
    clamp to the same schedule are timed once."""
    inner = getattr(sim, "_inner", sim)
    out = []
    for name in sorted(names):
        if name == "frontier_mode":
            out.append(("frontier",
                        bool(getattr(inner, "_frontier_skip", False)),
                        bool(getattr(inner, "_frontier_delta", False))))
        elif name == "prefetch_depth":
            out.append(("prefetch", int(getattr(inner, "_prefetch", 0))))
        elif name == "overlap_mode":
            out.append(("overlap", bool(getattr(inner, "_overlap",
                                                False))))
        elif name == "hier_mode":
            out.append(("hier", bool(getattr(inner, "_hier", False))))
        elif name == "sir_fuse":
            out.append(("sir_fuse", bool(getattr(inner, "_fuse",
                                                 False))))
        elif name == "frontier_threshold":
            out.append(("threshold",
                        float(getattr(inner, "frontier_threshold",
                                      0.0))))
    return tuple(out)


def _build(cfg, overrides: dict, n_peers):
    """One candidate build through the real engine table, clamp ledger
    captured.  Returns ``(sim, clamps)``."""
    from p2p_gossipprotocol_tpu.engines import build_simulator

    cfg2 = copy.deepcopy(cfg)
    for key, val in overrides.items():
        setattr(cfg2, key, val)
    clamps: list = []
    sim, _name = build_simulator(cfg2, n_peers=n_peers, clamps=clamps)
    return sim, clamps


def _time_sim(sim, rounds: int, repeats: int) -> float:
    """ms/round: one warm-up execution (compile/upload excluded), then
    the min wall over ``repeats`` fixed-round scans — min, not mean,
    because scheduler noise only ever adds time."""
    state = sim.init_state()
    sim.run(1, state=state, warmup=True)
    best = float("inf")
    for _ in range(max(1, repeats)):
        r = sim.run(rounds, state=state)
        best = min(best, float(r.wall_s))
    return best / rounds * 1e3


def tune_config(cfg, n_peers: int | None = None, *, rounds: int = 8,
                repeats: int = 2, path: str | None = None,
                force: bool = False, log=print) -> dict:
    """Sweep the legal static space for ``cfg`` and persist the winner
    in the tuning cache; returns the stored entry (or the fresh
    existing one when ``force`` is false and the signature is already
    cached un-stale).  ``path`` overrides the cache location
    (``GOSSIP_TUNING_CACHE`` otherwise)."""
    if cfg.engine not in ("aligned", "fleet"):
        raise ValueError(
            "the autotuner tunes the aligned engine family's "
            "performance statics — the edges engine has none, and the "
            "realgraph engine's statics (realgraph_pack_width / "
            "realgraph_scatter) resolve through the tuning chokepoint "
            "at build time, not through this timed sweep (its run() "
            "drives the edges-family loop, which the sweep harness "
            "cannot time) — set engine=aligned in the config")
    # fleet configs tune their underlying aligned scenarios: the
    # bucket batches these exact statics, and the packer signature
    # carries the resolved values, so one solo sweep serves both
    cfg = copy.deepcopy(cfg)
    cfg.engine = "aligned"
    with _cache_disabled():
        sim0, clamps0 = _build(cfg, {}, n_peers)
    sig = tuning_resolve.signature_for_sim(sim0)
    if not force:
        fresh = tuning_cache.lookup(sig, path=path)
        if fresh is not None:
            log(f"[tune] signature already cached "
                f"({tuning_cache.sig_key(sig)}) — use force=True to "
                "re-sweep")
            return fresh
    space = candidate_space(sim0, cfg)
    names = sorted(space)
    log(f"[tune] signature {tuning_cache.sig_key(sig)}")
    log(f"[tune] space: " + ", ".join(
        f"{k}={list(space[k])}" for k in names))

    timed: dict[tuple, tuple[float, dict]] = {}
    default_key = _resolved_key(sim0, names)
    default_ms = _time_sim(sim0, rounds, repeats)
    timed[default_key] = (default_ms, {})    # {} = the heuristic pick
    log(f"[tune] default: {default_ms:.3f} ms/round")

    with _cache_disabled():
        for combo in itertools.product(*(space[n] for n in names)):
            overrides = dict(zip(names, combo))
            try:
                sim, clamps = _build(cfg, overrides, n_peers)
            except ValueError:
                continue                   # illegal combo: never timed
            if any(any(n in c for n in overrides) for c in clamps
                   if c not in clamps0):
                continue      # the engine clamped a forced knob away
            key = _resolved_key(sim, names)
            if key in timed:
                continue                   # same effective schedule
            ms = _time_sim(sim, rounds, repeats)
            timed[key] = (ms, overrides)
            log("[tune] " + " ".join(f"{k}={v}"
                                     for k, v in overrides.items())
                + f": {ms:.3f} ms/round")

    best_key = min(timed, key=lambda k: timed[k][0])
    best_ms, best_overrides = timed[best_key]
    if best_ms >= default_ms * (1.0 - NOISE_FRAC):
        # inside the noise band: store the default pick explicitly so
        # the cache never encodes jitter as a schedule preference
        best_ms, best_overrides = default_ms, {}
    statics = _default_statics(sim0)
    statics.update(best_overrides)
    entry = tuning_cache.store(
        sig, statics, ms_per_round=best_ms,
        default_ms_per_round=default_ms,
        note={"n_peers": getattr(sim0, "n_peers", None)
              or getattr(getattr(sim0, "_inner", sim0).topo,
                         "n_peers", None),
              "rounds": rounds, "repeats": repeats,
              "candidates_timed": len(timed)},
        path=path)
    log(f"[tune] best: {best_ms:.3f} ms/round "
        f"({best_ms / default_ms:.3f}x default) — stored")
    return entry


def _default_statics(sim) -> dict:
    """The heuristics' resolved picks in config-key terms — the cache
    stores FULL static sets so a hit resolves every tunable at once."""
    inner = getattr(sim, "_inner", sim)
    out = {
        "prefetch_depth": int(getattr(inner, "_prefetch", 0)),
        "frontier_threshold": float(getattr(inner, "frontier_threshold",
                                            0.0) or 0.0),
    }
    if getattr(inner, "mode", "sir") == "sir":
        out["sir_fuse"] = int(bool(getattr(inner, "_fuse", False)))
    else:
        out["frontier_mode"] = int(bool(
            getattr(inner, "_frontier_delta", False)))
        out["overlap_mode"] = int(bool(getattr(inner, "_overlap",
                                               False)))
        out["hier_mode"] = int(bool(getattr(inner, "_hier", False)))
    return out


def tune_serve_chunk(cfg, *, n_req: int = 6, candidates=(4, 8, 16, 32),
                     path: str | None = None, log=print) -> dict:
    """Sweep the serving loop's admission cadence: time ``n_req``
    identical-shape requests end-to-end through an in-process resident
    server at each chunk length; store the winner under
    :func:`resolve.serve_signature`.  Each served scenario is bitwise
    its solo run at ANY chunk (tests/test_serve.py), so this too is a
    pure schedule race."""
    from p2p_gossipprotocol_tpu.serve.service import GossipService

    rounds = cfg.serve_rounds or cfg.rounds or 64
    slots = cfg.serve_slots
    results = {}
    default_chunk = tuning_resolve.SERVE_CHUNK_DEFAULT
    with _cache_disabled():
        for chunk in dict.fromkeys((default_chunk, *candidates)):
            svc = GossipService(cfg, chunk=chunk).start()
            t0 = time.perf_counter()
            rids = [svc.submit({"prng_seed": s}) for s in range(n_req)]
            for rid in rids:
                svc.result(rid, timeout=600)
            wall = time.perf_counter() - t0
            svc.drain()
            results[chunk] = wall / n_req * 1e3
            log(f"[tune] serve_chunk={chunk}: "
                f"{results[chunk]:.1f} ms/request")
    default_ms = results[default_chunk]
    best_chunk = min(results, key=results.get)
    if results[best_chunk] >= default_ms * (1.0 - NOISE_FRAC):
        best_chunk = default_chunk
    entry = tuning_cache.store(
        tuning_resolve.serve_signature(slots, rounds),
        {"serve_chunk": int(best_chunk)},
        ms_per_round=results[best_chunk],
        default_ms_per_round=default_ms,
        note={"unit": "ms_per_request", "n_req": n_req}, path=path)
    log(f"[tune] serve_chunk winner: {best_chunk}")
    return entry

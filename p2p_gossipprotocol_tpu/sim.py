"""Simulator: the whole reference network as one jitted scan loop.

Where the reference runs n processes × 4 threads each, blocking on sockets
(SURVEY.md §3.1), the simulator advances every peer in lockstep: one
``lax.scan`` step = one gossip round = one message_interval tick of the
reference's wall-clock.  Per-round metrics (coverage, frontier size, live
peers, deliveries, evictions) are the scan's ``ys`` — the structured
observability the reference lacks (SURVEY §5).

Two execution paths:
  * :meth:`Simulator.run` — fixed-round ``lax.scan``, full metric history.
  * :meth:`Simulator.run_to_coverage` — ``lax.while_loop`` that stops at a
    target coverage, for benchmarking time-to-99%.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from p2p_gossipprotocol_tpu import faults as faults_lib
from p2p_gossipprotocol_tpu import graph as graph_lib
from p2p_gossipprotocol_tpu.graph import Topology
from p2p_gossipprotocol_tpu.liveness import (ChurnConfig, churn_step,
                                             strike_and_rewire)
from p2p_gossipprotocol_tpu.models.byzantine import inject_byzantine
from p2p_gossipprotocol_tpu.models.gossip import make_round_fn
from p2p_gossipprotocol_tpu.models.sir import sir_round
from p2p_gossipprotocol_tpu.state import (GossipState, SIRState,
                                          init_gossip_state, init_sir_state)


def coverage_of(state: GossipState, n_honest: int | None = None,
                stagger: int = 0) -> jax.Array:
    """Mean over (honest) message columns of the fraction of live honest
    peers that have seen the message.

    With staggered generation (``stagger=k>0``) the mean runs over the
    columns GENERATED so far — a rumor that doesn't exist yet (or whose
    source died before its activation round, so it never will) can't
    count against coverage, exactly as the reference's
    coverage-of-existing-messages would read.  Generated is derived
    from the seen matrix itself: an injected column holds its source
    bit forever (seen bits never clear), a never-injected one holds
    nothing."""
    ok = state.alive & ~state.byzantine
    denom = jnp.maximum(jnp.sum(ok, dtype=jnp.int32), 1)
    per_msg = jnp.sum(state.seen & ok[:, None], axis=0,
                      dtype=jnp.int32) / denom
    n_h = state.n_msgs if n_honest is None else n_honest
    if n_h < state.n_msgs:
        per_msg = per_msg[:n_h]
    if stagger > 0:
        n_gen = jnp.sum(jnp.any(state.seen[:, :n_h], axis=0),
                        dtype=jnp.int32)
        return jnp.sum(per_msg) / jnp.maximum(n_gen, 1)
    return jnp.mean(per_msg)


class _FromMetrics:
    """Shared assembly from a scan's stacked metrics dict — every
    engine's ``run()`` ends with ``Result.from_metrics(...)``, so the
    result surface is defined in exactly one place per class."""

    @classmethod
    def from_metrics(cls, state, topo, ys: dict, wall_s: float):
        import dataclasses

        names = [f.name for f in dataclasses.fields(cls)
                 if f.name not in ("state", "topo", "wall_s")]
        return cls(state=state, topo=topo, wall_s=wall_s,
                   **{k: np.asarray(ys[k]) for k in names})


@dataclass
class SimResult(_FromMetrics):
    """Host-side results of a run."""

    state: GossipState
    topo: Topology
    coverage: np.ndarray       # float32[rounds]
    deliveries: np.ndarray     # int32[rounds] (edge engine); float32 from
    frontier_size: np.ndarray  #   the aligned engines — exact popcount
    live_peers: np.ndarray     #   pairs combine to float so totals past
    evictions: np.ndarray      #   2^31 bits don't wrap (aligned.py)
    redeliveries: np.ndarray = None  # receipts of already-seen messages
    wall_s: float = 0.0        #   (the degradation metric link faults
    #                              inflate; 0 under aligned fuse_update,
    #                              whose kernel never materializes recv)

    def rounds_to(self, target: float = 0.99) -> int:
        """First 1-indexed round reaching target coverage, or -1."""
        hit = np.nonzero(self.coverage >= target)[0]
        return int(hit[0]) + 1 if hit.size else -1

    @property
    def total_deliveries(self) -> int:
        return int(self.deliveries.sum())


@dataclass
class Simulator:
    """Owns a topology + round semantics; state flows through functionally.

    Parameters mirror the config system: ``mode`` (push|pull|pushpull,
    push being the reference's semantics), ``fanout`` (0 = flood, the
    reference's broadcast), churn/byzantine knobs, and the liveness
    3-strike rule (max_missed_pings, honored from config unlike the
    reference — SURVEY §2-C2).
    """

    topo: Topology
    n_msgs: int = 16
    mode: str = "push"
    fanout: int = 0
    churn: ChurnConfig = field(default_factory=ChurnConfig)
    byzantine_fraction: float = 0.0
    n_honest_msgs: int | None = None   # None → all columns honest
    max_strikes: int = 3
    rewire: bool = True
    #: rounds between successive message activations: column m enters at
    #: its source in round m*k (messageGenerationLoop cadence,
    #: peer.cpp:357-377).  0 = every rumor exists from round 0.
    message_stagger: int = 0
    seed: int = 0
    transport: object | None = None   # Transport; None → JaxTransport
    #: declarative fault plan (faults.FaultPlan): link drop, relay delay,
    #: partition windows, crash/recovery schedules.  None = the plain
    #: protocol, compiled exactly as before the fault plane existed.
    faults: object | None = None

    def __post_init__(self):
        if self.faults is not None:
            self.faults.validate()
        self._round_fn = make_round_fn(self.mode, self.fanout,
                                       transport=self.transport,
                                       faults=self.faults)
        self._n_honest = (self.n_honest_msgs
                          if self.n_honest_msgs is not None else self.n_msgs)

        # One jitted program per instance (rounds is a static arg), so
        # repeated run() calls — parameter sweeps, benchmarks — reuse the
        # compiled scan instead of recompiling a fresh closure every call.
        def _scan(st, tp, rounds):
            def body(carry, _):
                s, t = carry
                s, t, metrics = self.step(s, t)
                return (s, t), metrics
            return jax.lax.scan(body, (st, tp), None, length=rounds)

        self._scan_jit = jax.jit(_scan, static_argnums=2)
        self._loop_cache: dict = {}   # (target, max_rounds) -> compiled
        if self.message_stagger > 0:
            self._message_plan()   # eager: a traced cache would leak

    # ------------------------------------------------------------------
    def init_state(self, sources=None) -> GossipState:
        if sources is not None and self.message_stagger > 0:
            raise ValueError(
                "custom sources are incompatible with message_stagger "
                "(staggered generation re-derives the default placement "
                "each round)")
        key = jax.random.PRNGKey(self.seed)
        return init_gossip_state(self.topo, self.n_msgs, key,
                                 sources=sources,
                                 byzantine_fraction=self.byzantine_fraction,
                                 n_honest_msgs=self._n_honest,
                                 stagger=self.message_stagger)

    def _message_plan(self) -> jax.Array:
        """Per-column source peers (state.message_plan), cached eagerly
        so the per-round generation gate costs O(n_msgs), not a fresh
        O(n_peers) placement every round."""
        if getattr(self, "_plan_cache", None) is None:
            from p2p_gossipprotocol_tpu.state import message_plan

            self._plan_cache = message_plan(
                self.seed, self.topo.n_peers, self.byzantine_fraction,
                self.n_msgs, self._n_honest)
        return self._plan_cache

    def _generate_messages(self, state: GossipState,
                           sources=None) -> GossipState:
        """Staggered generation: on round ``m * k`` inject column m's
        bit at its source peer (the vectorized messageGenerationLoop
        tick, peer.cpp:357-377).  Runs after churn, so a source that
        died before its activation round never generates — like the
        reference's generation thread stopping with its process.  The
        injected frontier bit is relayed THIS round, matching how the
        round-0 seeding is consumed by the first step."""
        k = self.message_stagger
        # ``sources`` override: the fleet/serve bucket passes each
        # slot's own plan row through the vmapped round (the solo path
        # always reads the cached plan — identical values either way)
        sources = self._message_plan() if sources is None else sources
        col = jnp.arange(self.n_msgs, dtype=jnp.int32)
        gen = ((col * k == state.round) & (col < self._n_honest)
               & state.alive[sources] & ~state.byzantine[sources])
        bits = jnp.zeros_like(state.seen).at[sources, col].max(gen)
        return state.replace(seen=state.seen | bits,
                             frontier=state.frontier | bits)

    # ------------------------------------------------------------------
    def step(self, state: GossipState, topo: Topology, msg_srcs=None
             ) -> tuple[GossipState, Topology, dict]:
        """One full round: churn → liveness/rewire → (byz inject) → gossip.

        ``msg_srcs`` (optional) overrides the staggered-generation
        source row — the batched bucket's per-slot lane; None (every
        solo path) reads the cached plan."""
        key, k_churn, k_rewire = jax.random.split(state.key, 3)
        state = state.replace(key=key)
        alive = churn_step(k_churn, state.alive, state.round, self.churn)
        if self.faults is not None and (self.faults.crash
                                        or self.faults.recover):
            # Scheduled crash/recovery (the fault plane's one-shot
            # complement to the continuous churn hazard).  Crashes are
            # real deaths — the liveness strikes below observe them,
            # unlike partitions, which sever transfers only.
            n = alive.shape[0]
            alive = faults_lib.schedule_step(
                self.faults, faults_lib.round_key(self.faults, state.round),
                alive, jnp.ones(n, bool), state.round,
                lambda k: jax.random.uniform(k, (n,)))
        state = state.replace(alive=alive)
        topo, strikes, n_evict = strike_and_rewire(
            k_rewire, topo, state.edge_strikes, alive,
            max_strikes=self.max_strikes, rewire=self.rewire)
        state = state.replace(edge_strikes=strikes)
        if self._n_honest < self.n_msgs:
            state = inject_byzantine(state, self._n_honest)
        if self.message_stagger > 0:
            state = self._generate_messages(state, sources=msg_srcs)
        state, deliveries, redeliveries = self._round_fn(state, topo)
        metrics = {
            "coverage": coverage_of(state, self._n_honest,
                                    stagger=self.message_stagger),
            "deliveries": deliveries,
            "frontier_size": jnp.sum(state.frontier, dtype=jnp.int32),
            "live_peers": jnp.sum(state.alive, dtype=jnp.int32),
            "evictions": n_evict,
            "redeliveries": redeliveries,
        }
        return state, topo, metrics

    # ------------------------------------------------------------------
    def run(self, rounds: int, state: GossipState | None = None,
            topo: Topology | None = None) -> SimResult:
        """Fixed-round scan with full metric history."""
        import time as _time

        state = self.init_state() if state is None else state
        topo = self.topo if topo is None else topo

        t0 = _time.perf_counter()
        (state, topo), ys = self._scan_jit(state, topo, rounds)
        jax.block_until_ready(state.seen)
        wall = _time.perf_counter() - t0
        return SimResult.from_metrics(state, topo, ys, wall)

    # ------------------------------------------------------------------
    def run_to_coverage(self, target: float = 0.99, max_rounds: int = 256,
                        state: GossipState | None = None,
                        warmup: bool = True, check_every: int = 1
                        ) -> tuple[GossipState, Topology, int, float]:
        """while_loop until coverage ≥ target; returns
        (state, topo, rounds_run, wall_seconds).  This is the benchmark
        path (BASELINE north star: 1M peers to 99% in < 2 s).  With
        ``warmup`` the compiled program is executed once untimed first, so
        the wall excludes the one-time program-upload cost remote PJRT
        backends pay on first execution.

        ``check_every=K`` is the same chunked-census option as
        AlignedSimulator.run_to_coverage (see its docstring for the
        barrier rationale): convergence may overshoot by < K rounds
        (counted in the reported wall/rounds), ``max_rounds`` stays a
        hard cap via a per-round tail loop."""
        import time as _time

        if check_every < 1:
            raise ValueError("check_every must be >= 1")
        state = self.init_state() if state is None else state

        cache_key = (target, max_rounds, check_every)
        if cache_key not in self._loop_cache:
            from p2p_gossipprotocol_tpu.state import (build_coverage_loop,
                                                      stagger_sched_end)

            sched_end = stagger_sched_end(self._n_honest,
                                          self.message_stagger)
            go = jax.jit(build_coverage_loop(
                self.step, target=target, max_rounds=max_rounds,
                check_every=check_every, sched_end=sched_end))

            # compile once per (target, max_rounds, check_every); compile
            # time excluded from the timed run
            self._loop_cache[cache_key] = go.lower(state,
                                                   self.topo).compile()
        go_c = self._loop_cache[cache_key]
        if warmup:
            out = go_c(state, self.topo)
            jax.device_get(out[0].round)
        t0 = _time.perf_counter()
        st, tp, cov = go_c(state, self.topo)
        # device_get of a scalar forces real completion — block_until_ready
        # on AOT-executable outputs returns early on some PJRT backends,
        # which would report fantasy wall-clock numbers.
        rounds_run = int(jax.device_get(st.round))
        wall = _time.perf_counter() - t0
        return st, tp, rounds_run, wall

    # ------------------------------------------------------------------
    @classmethod
    def from_config(cls, cfg, n_peers: int | None = None) -> "Simulator":
        """Build simulator + overlay from a :class:`NetworkConfig`."""
        topo = graph_lib.from_config(cfg, n_peers=n_peers)
        n_msgs = cfg.n_messages or cfg.max_message_count
        plan = faults_lib.plan_from_config(cfg)
        # The plan's byzantine knob is the unified entry to the existing
        # adversary machinery (drop = suppression, equivocation = junk
        # injection) — merged, never silently overriding an explicit
        # byzantine_fraction.
        byz = max(cfg.byzantine_fraction,
                  plan.byzantine if plan else 0.0)
        n_junk = 0
        if byz > 0.0:
            n_junk = max(1, n_msgs // 4)
        churn = ChurnConfig(rate=cfg.churn_rate) if cfg.churn_rate else \
            ChurnConfig()
        return cls(
            topo=topo,
            n_msgs=n_msgs + n_junk,
            mode=cfg.mode,
            fanout=cfg.fanout,
            churn=churn,
            byzantine_fraction=byz,
            n_honest_msgs=n_msgs if n_junk else None,
            max_strikes=cfg.max_missed_pings,
            message_stagger=cfg.message_stagger,
            seed=cfg.prng_seed,
            faults=plan if plan and plan.engine_active() else None,
        )


@dataclass
class SIRResult(_FromMetrics):
    """Host-side epidemic curve (the per-round S/I/R census)."""

    state: SIRState
    topo: Topology
    susceptible: np.ndarray     # int32[rounds]
    infected: np.ndarray        # int32[rounds]
    recovered: np.ndarray       # int32[rounds]
    new_infections: np.ndarray  # int32[rounds]
    live_peers: np.ndarray      # int32[rounds]
    wall_s: float = 0.0

    @property
    def peak_infected(self) -> int:
        return int(self.infected.max())

    @property
    def attack_rate(self) -> float:
        """Fraction of the population ever infected (R + I at the end)."""
        n = self.state.n_peers
        return float((self.infected[-1] + self.recovered[-1]) / n)

    def rounds_to_extinction(self) -> int:
        """First 1-indexed round with zero infected, or -1."""
        hit = np.nonzero(self.infected == 0)[0]
        return int(hit[0]) + 1 if hit.size else -1


@dataclass
class SIRSimulator:
    """SIR epidemic spread over the overlay (BASELINE.json config 3:
    BA-100k) — the same scan/metrics machinery as the gossip Simulator,
    consuming the ``sir_beta``/``sir_gamma`` config keys end to end.

    The reference has no epidemic model (its gossip IS the SI model);
    this closes the parsed-but-ignored-key defect class the reference's
    config system suffers from (SURVEY.md §2-C2): every ``sir_*`` key is
    consumed here and nowhere else."""

    topo: Topology
    beta: float = 0.3
    gamma: float = 0.1
    n_seeds: int = 1
    churn: ChurnConfig = field(default_factory=ChurnConfig)
    seed: int = 0

    def __post_init__(self):
        if not 0.0 <= self.beta <= 1.0:
            raise ValueError("sir_beta must be in [0, 1]")
        if not 0.0 <= self.gamma <= 1.0:
            raise ValueError("sir_gamma must be in [0, 1]")

        # topo rides as a traced argument, not a closure capture — a
        # captured topology is baked into the HLO as a constant, which
        # blows the remote-compile transport's body limit at scale
        # (HTTP 413; first hit by the aligned SIR engine at 32M)
        def _scan(st, tp, rounds):
            def body(carry, _):
                s, metrics = self.step(carry, tp)
                return s, metrics
            return jax.lax.scan(body, st, None, length=rounds)

        self._scan_jit = jax.jit(_scan, static_argnums=2)

    # ------------------------------------------------------------------
    def init_state(self) -> SIRState:
        return init_sir_state(self.topo, jax.random.PRNGKey(self.seed),
                              n_seeds=self.n_seeds)

    # ------------------------------------------------------------------
    def step(self, state: SIRState, topo: Topology | None = None
             ) -> tuple[SIRState, dict]:
        """One round: churn → masked SIR contact/recovery → census."""
        topo = self.topo if topo is None else topo
        key, k_churn = jax.random.split(state.key)
        alive = churn_step(k_churn, state.alive, state.round, self.churn)
        state = state.replace(alive=alive, key=key)
        state, n_new = sir_round(state, topo, beta=self.beta,
                                 gamma=self.gamma)
        metrics = {
            "susceptible": jnp.sum(state.susceptible, dtype=jnp.int32),
            "infected": jnp.sum(state.infected, dtype=jnp.int32),
            "recovered": jnp.sum(state.recovered, dtype=jnp.int32),
            "new_infections": n_new,
            "live_peers": jnp.sum(state.alive, dtype=jnp.int32),
        }
        return state, metrics

    # ------------------------------------------------------------------
    def run(self, rounds: int, state: SIRState | None = None) -> SIRResult:
        import time as _time

        state = self.init_state() if state is None else state
        t0 = _time.perf_counter()
        state, ys = self._scan_jit(state, self.topo, rounds)
        jax.block_until_ready(state.compartment)
        wall = _time.perf_counter() - t0
        return SIRResult.from_metrics(state, self.topo, ys, wall)

    # ------------------------------------------------------------------
    @classmethod
    def from_config(cls, cfg, n_peers: int | None = None) -> "SIRSimulator":
        plan = faults_lib.plan_from_config(cfg)
        if plan is not None and plan.engine_active():
            raise ValueError(
                "fault plans apply to the gossip modes — the SIR model "
                "has no message-transfer path to fault (use churn_rate "
                "for its peer-level failures)")
        topo = graph_lib.from_config(cfg, n_peers=n_peers)
        return cls(
            topo=topo,
            beta=cfg.sir_beta,
            gamma=cfg.sir_gamma,
            churn=(ChurnConfig(rate=cfg.churn_rate) if cfg.churn_rate
                   else ChurnConfig()),
            seed=cfg.prng_seed,
        )

"""CLI entry point: ``peer_network <config_file>``.

Preserves the reference's invocation exactly (main.cpp:29-34: one
positional config-file argument, usage message on error, SIGINT/SIGTERM
graceful shutdown, config printed at startup) and adds what it lacks:

* ``--backend {jax,socket}`` — TPU simulation vs n-terminal socket mode;
* ``--role {peer,seed}``     — a real entry point for the seed role the
  reference defined but never wired up (SURVEY §3.5);
* ``--n-peers/--rounds/--mode/...`` — simulation overrides;
* a machine-readable result line (JSON) after a jax-backend run.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import time

from p2p_gossipprotocol_tpu.config import ConfigError, NetworkConfig


def print_usage(prog: str) -> None:
    # Text shape mirrors printUsage (main.cpp:24-27).
    print(f"Usage: {prog} <config_file>", file=sys.stderr)
    print("  config_file: Path to network configuration file",
          file=sys.stderr)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="peer_network", add_help=True,
        description="TPU-native gossip network "
                    "(capabilities of PareenShah27/P2P-GossipProtocol)")
    p.add_argument("config_file", help="network configuration file")
    p.add_argument("--backend", choices=["jax", "socket"], default=None,
                   help="override config backend")
    p.add_argument("--role", choices=["peer", "seed"], default="peer",
                   help="socket mode: run a peer or a seed server")
    p.add_argument("--n-peers", type=int, default=None,
                   help="jax mode: simulated peer count")
    p.add_argument("--rounds", type=int, default=None,
                   help="jax mode: rounds to simulate")
    p.add_argument("--mode", choices=["push", "pull", "pushpull", "sir"],
                   default=None,
                   help="gossip mode override (sir = epidemic model)")
    p.add_argument("--graph",
                   choices=["reference", "er", "ba", "powerlaw"],
                   default=None,
                   help="jax mode: overlay model override (same as the "
                        "graph= config key)")
    p.add_argument("--engine",
                   choices=["edges", "aligned", "fleet", "realgraph"],
                   default=None,
                   help="jax mode: exact edge-list engine, the "
                        "hardware-aligned pallas engine (1M+ peers), "
                        "the fleet engine (batched multi-scenario "
                        "sweeps — needs --sweep), or the real-graph "
                        "SpMV engine over an ingested edge list "
                        "(--graph-file; bitwise == edges); default: "
                        "the config's engine= key (edges)")
    p.add_argument("--graph-file", default=None, metavar="PATH",
                   help="jax mode, engine=realgraph: edge-list file "
                        "(whitespace/CSV/SNAP — sniffed) or a prebuilt "
                        ".csr artifact directory; same as the "
                        "graph_file= config key.  First ingest caches "
                        "a CRC-verified CSR artifact next to the file")
    p.add_argument("--sweep", default=None, metavar="SPECS",
                   help="jax mode: serve a batched multi-scenario sweep "
                        "(engine=fleet): SPECS is a JSONL file, one "
                        "scenario of config-key overrides per line "
                        "(e.g. {\"prng_seed\": 3, \"mode\": \"pull\", "
                        "\"fault_link_drop\": 0.1}).  Scenarios bucket "
                        "by program shape and run batched on one "
                        "device; every result is bitwise-identical to "
                        "the scenario's solo run (docs/ARCHITECTURE.md "
                        "fleet section)")
    p.add_argument("--sweep-results", default=None, metavar="PATH",
                   help="fleet mode: write the per-scenario results "
                        "table (JSONL) here; default: the "
                        "sweep_results= config key, else rows print to "
                        "stdout")
    p.add_argument("--serve", action="store_true",
                   help="jax mode: run as a RESIDENT gossip-sim server "
                        "(serve/): scenarios arrive as sweep-line "
                        "config dicts over local_ip:local_port "
                        "(wire_format= framing), are admitted into hot "
                        "fleet buckets at round boundaries "
                        "(continuous batching — zero recompilation on "
                        "a signature hit), and every result is "
                        "bitwise-identical to the scenario's solo "
                        "run.  SIGINT/SIGTERM with --checkpoint-dir "
                        "salvages in-flight buckets + the queue and "
                        "exits 75; --serve --resume re-hydrates them. "
                        "Config twins: serve=1 and the serve_* keys "
                        "(docs/ARCHITECTURE.md \"The serving seam\")")
    p.add_argument("--serve-fleet", action="store_true",
                   help="jax mode: run the FAULT-TOLERANT serving "
                        "fleet (serve/router.py): serve_replicas "
                        "supervised --serve replica processes behind "
                        "a signature-affinity router on "
                        "local_ip:local_port.  Clients speak the "
                        "unchanged submit/result/stats/drain "
                        "protocol; same-signature requests stick to "
                        "one replica (zero-recompile admission "
                        "survives the hop); a SIGKILLed replica's "
                        "in-flight requests re-admit onto survivors — "
                        "zero lost, zero duplicated, every result "
                        "still bitwise its solo run "
                        "(docs/ROBUSTNESS.md \"The serving fleet\")")
    p.add_argument("--serve-heartbeat", default=None, metavar="PATH",
                   help="serve-replica mode (set by the fleet "
                        "router): stamp the supervision plane's "
                        "heartbeat file at PATH sub-second, carrying "
                        "the BOUND serve port (an EADDRINUSE rebind "
                        "is discovered through it), and refresh the "
                        "salvage checkpoint periodically so a SIGKILL "
                        "leaves a recent manifest to recover from")
    p.add_argument("--serve-rank", type=int, default=0, metavar="R",
                   help="serve-replica mode: this replica's rank in "
                        "the fleet (stamped into the heartbeat)")
    p.add_argument("--federate", action="store_true",
                   help="jax mode: run the GLOBAL serving federation "
                        "(serve/federation.py): federate_fleets "
                        "independent --serve-fleet children (each the "
                        "full router + replicas) behind one "
                        "client-facing wire on local_ip:local_port.  "
                        "Requests route to the fleet already warm for "
                        "their signature (park manifests gossip "
                        "through the fleet directory); a whole fleet's "
                        "SIGKILL adopts its salvaged rows and "
                        "re-admits in-flight rids onto survivors "
                        "(zero lost, zero duplicated); per-tenant "
                        "budgets shed an overloading tenant's excess "
                        "with a typed reason, never its neighbors' "
                        "(docs/ROBUSTNESS.md \"The federation\")")
    p.add_argument("--fleet-name", default="", metavar="NAME",
                   help="serve-fleet mode (set by the federation): "
                        "this fleet's directory name, stamped into "
                        "its fleet-kind heartbeat and salvage "
                        "manifest")
    p.add_argument("--fleet-epoch", type=int, default=0, metavar="E",
                   help="serve-fleet mode (set by the federation): "
                        "this fleet's generation number — manifests "
                        "stamp it, and the federation refuses to "
                        "adopt rows from any epoch but the one it "
                        "assigned (the stale-manifest fence)")
    p.add_argument("--mesh-devices", type=int, default=None, metavar="N",
                   help="jax mode: shard the peer axis over an N-device "
                        "mesh (ShardedSimulator / "
                        "AlignedShardedSimulator); 0 = single device; "
                        "default: the mesh_devices= config key")
    p.add_argument("--msg-shards", type=int, default=None, metavar="M",
                   help="with --engine aligned and --mesh-devices N: "
                        "also shard the message planes, as an "
                        "M x (N/M) (msgs x peers) 2-D mesh "
                        "(Aligned2DShardedSimulator); 0 = peers only; "
                        "default: the msg_shards= config key")
    p.add_argument("--target-coverage", type=float, default=0.99)
    p.add_argument("--fault-plan", default=None, metavar="SPEC",
                   help="unified fault injection (faults.FaultPlan), "
                        "e.g. 'drop=0.2,delay=0.1,partition=4:12,"
                        "groups=2,crash=3:0.3,recover=16:0.5'; "
                        "overrides the fault_* config keys.  jax mode: "
                        "seed-deterministic link/partition/crash masks "
                        "in every engine; socket mode: wire-level "
                        "drop/delay/duplication")
    p.add_argument("--local-ip", default=None)
    p.add_argument("--local-port", type=int, default=None)
    p.add_argument("--wire-format", choices=["json", "framed"],
                   default=None,
                   help="socket mode: reference-compatible unframed JSON "
                        "or length-framed (same as the wire_format= "
                        "config key)")
    p.add_argument("--checkpoint-every", type=int, default=0, metavar="N",
                   help="jax mode: checkpoint the full simulation state "
                        "every N rounds (orbax) into --checkpoint-dir")
    p.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                   help="where checkpoints live (required with "
                        "--checkpoint-every / --resume)")
    p.add_argument("--resume", action="store_true",
                   help="jax mode: continue from the checkpoint in "
                        "--checkpoint-dir; the completed run's summary "
                        "is identical to an uninterrupted one.  The "
                        "checkpoint is canonical/layout-free: resuming "
                        "on a different --engine layout (mesh-devices/"
                        "msg-shards, or a single device) continues "
                        "bitwise-identically.  A run interrupted by "
                        "SIGINT/SIGTERM salvages a checkpoint and exits "
                        "75 (resumable)")
    p.add_argument("--supervise", action="store_true",
                   help="jax mode, engine=aligned: run the scenario as "
                        "a supervised multi-process job "
                        "(runtime/supervisor.py): supervise_workers "
                        "worker processes under the health plane — "
                        "round-stamped heartbeats, traffic-model-"
                        "derived deadlines, hung/dead worker "
                        "detection, and deterministic shrink-to-"
                        "survivors recovery from the last elastic "
                        "checkpoint (needs --checkpoint-dir for "
                        "resume-instead-of-restart).  Config twins: "
                        "supervise=1 and the supervise_* keys")
    p.add_argument("--telemetry", action="store_true",
                   help="jax mode: turn on the flight-recorder "
                        "telemetry plane (telemetry/): nested spans "
                        "(run > chunk > exchange; serve request "
                        "ledgers), live counters + roofline_frac "
                        "reconciled against traffic_model(), and "
                        "atomic flight-recorder dumps on crash / "
                        "SIGTERM salvage / demand.  Observational by "
                        "contract: zero device computation, results "
                        "bitwise-identical on or off "
                        "(docs/OBSERVABILITY.md).  Config twins: "
                        "telemetry=1 and the telemetry_* keys; env "
                        "twin GOSSIP_TELEMETRY=1")
    p.add_argument("--metrics-jsonl", default=None, metavar="PATH",
                   help="write per-round metrics as JSONL")
    p.add_argument("--profile-dir", default=None, metavar="DIR",
                   help="jax.profiler trace directory for the run")
    p.add_argument("--quiet", action="store_true")
    return p


def _run_sim(sim, rounds, args, cfg, engine, stop):
    """sim.run(rounds), optionally through the checkpoint runner (the
    CLI face of utils.checkpoint.run_with_checkpoints: kill a run, pass
    --resume, get the summary an uninterrupted run would print).

    Under the runner, SIGINT/SIGTERM flip the ``stop`` flag instead of
    killing the process: the in-flight chunk completes, a SALVAGE
    checkpoint persists at that round boundary, and main exits with the
    resumable code (utils.checkpoint.EX_RESUMABLE, 75) that
    benchmarks/tpu_watchdog.sh turns into a --resume re-invocation —
    the TPU-preemption survival path."""
    if args.checkpoint_every > 0 or args.resume:
        from p2p_gossipprotocol_tpu.engines import config_keys
        from p2p_gossipprotocol_tpu.utils.checkpoint import \
            run_with_checkpoints

        def handler(signum, frame):
            print("\nReceived signal to terminate — salvage checkpoint "
                  "at the next round boundary, then exiting resumable "
                  "(code 75; re-run with --resume).", file=sys.stderr)
            stop["flag"] = True

        signal.signal(signal.SIGINT, handler)
        signal.signal(signal.SIGTERM, handler)
        return run_with_checkpoints(
            sim, rounds, every=args.checkpoint_every or rounds,
            directory=args.checkpoint_dir, resume=args.resume,
            should_stop=lambda: stop["flag"],
            config_keys=config_keys(cfg, n_peers=args.n_peers),
            engine=engine)
    return sim.run(rounds)


def _run_jax(cfg: NetworkConfig, args) -> int:
    # build_simulator probes the backend hang-proof first
    # (engines.probe_backend): a dead TPU tunnel degrades to a labeled
    # CPU run instead of freezing the CLI in backend init.
    from p2p_gossipprotocol_tpu.engines import build_simulator
    from p2p_gossipprotocol_tpu.utils import metrics as metrics_lib

    rounds = args.rounds or cfg.rounds or 64
    clamps: list[str] = []
    try:
        # THE engine-selection table (engines.build_simulator) — shared
        # with wrapper.Peer, so CLI flags and config keys cannot drift.
        sim, engine = build_simulator(
            cfg, n_peers=args.n_peers, mesh_devices=args.mesh_devices,
            msg_shards=args.msg_shards, clamps=clamps)
    except ValueError as e:
        # fail cleanly (values --engine edges accepts but the aligned
        # ceilings reject, impossible mesh layouts, ...) instead of
        # leaking a traceback
        print(f"Error: {e}", file=sys.stderr)
        return 1
    for c in clamps:
        print(f"Warning: engine clamped {c}", file=sys.stderr)
    if engine == "fleet":
        return _run_fleet(sim, cfg, args, rounds)
    n = sim.topo.n_peers
    if not args.quiet:
        if cfg.mode == "sir":
            detail = (f"{sim.topo.n_slots} slots/peer"
                      if engine.startswith("aligned")
                      else f"{int(sim.topo.n_edges())} edges")
            print(f"[jax/sir] simulating {n} peers, "
                  f"beta={cfg.sir_beta:g}, gamma={cfg.sir_gamma:g}, "
                  f"{detail}, engine={engine}")
        elif engine.startswith("aligned"):
            print(f"[jax/aligned] simulating {n} peers, {sim.n_msgs} "
                  f"messages, mode={sim.mode}, "
                  f"{sim.topo.n_slots} slots/peer, "
                  f"churn={cfg.churn_rate:g}, "
                  f"byzantine={cfg.byzantine_fraction:g}, "
                  f"engine={engine}")
        elif engine == "realgraph":
            pk = sim._pack
            print(f"[jax/realgraph] simulating {n} peers, "
                  f"{sim.n_msgs} messages, mode={sim.mode}, "
                  f"{pk.n_edges} edges in {len(pk.blocks)} "
                  f"degree-class blocks (width cap {pk.width_cap}), "
                  f"delivery={'scatter' if sim._scatter else 'gather'}, "
                  f"graph={cfg.graph_file or cfg.graph}, "
                  f"engine={engine}")
        else:
            print(f"[jax] simulating {n} peers, "
                  f"{sim.n_msgs} messages, mode={sim.mode}, "
                  f"{int(sim.topo.n_edges())} edges, engine={engine}")
    from p2p_gossipprotocol_tpu.utils.checkpoint import (CheckpointError,
                                                         EX_RESUMABLE)

    stop = {"flag": False}
    try:
        with metrics_lib.profile(args.profile_dir):
            res = _run_sim(sim, rounds, args, cfg, engine, stop)
    except CheckpointError as e:
        # named, actionable (fingerprint drift with the offending keys,
        # corrupt generations, impossible migration target) — never an
        # orbax traceback
        print(f"Error: {e}", file=sys.stderr)
        return 1
    if res is None:
        # interrupted before the first chunk completed: nothing salvaged
        print("Error: interrupted before the first checkpoint chunk "
              "completed — nothing salvaged (resume an earlier "
              "checkpoint if one exists)", file=sys.stderr)
        return EX_RESUMABLE if args.resume else 1
    graph_backend = (cfg.graph_backend if engine.startswith("edges")
                     else None)
    if cfg.mode == "sir":
        _report_sir(res, n_peers=n, engine=engine, args=args,
                    metrics_lib=metrics_lib, clamps=clamps or None,
                    graph_backend=graph_backend)
    else:
        _report(res, sim, n_peers=n, engine=engine, args=args,
                metrics_lib=metrics_lib, clamps=clamps or None,
                graph_backend=graph_backend)
    done = len(res.infected if cfg.mode == "sir" else res.coverage)
    if stop["flag"] and done < rounds:
        # flight-recorder dump alongside the exit-75 salvage: the
        # preempted run's spans/events/counters land next to its
        # checkpoint (or the configured telemetry dump dir)
        from p2p_gossipprotocol_tpu import telemetry

        telemetry.event("salvage", kind_detail="cli",
                        rounds_done=done, rounds=rounds)
        telemetry.dump("sigterm_salvage",
                       directory=(cfg.telemetry_dump_dir
                                  or args.checkpoint_dir))
        print(f"[checkpoint] salvage checkpoint covers {done}/{rounds} "
              "rounds — exiting resumable (75)", file=sys.stderr)
        return EX_RESUMABLE
    return 0


def _run_fleet(sweep, cfg, args, rounds) -> int:
    """Drive a fleet sweep (engine=fleet): per-bucket batched serving,
    a per-scenario JSONL results table, and the same preemption
    contract as the solo checkpoint runner — SIGINT/SIGTERM salvage the
    in-flight bucket at the next chunk boundary and exit 75
    (resumable); --resume skips completed buckets and continues the
    interrupted one bitwise."""
    from p2p_gossipprotocol_tpu.utils.checkpoint import (CheckpointError,
                                                         EX_RESUMABLE)

    # sweep_target=0 (the default) falls back to --target-coverage;
    # --target-coverage 0 disables convergence masking entirely (every
    # scenario runs the full fixed round count).
    target = cfg.sweep_target or args.target_coverage
    target = target if target > 0 else None
    stop = {"flag": False}
    if args.checkpoint_dir:
        def handler(signum, frame):
            print("\nReceived signal to terminate — salvaging the "
                  "in-flight bucket at the next chunk boundary, then "
                  "exiting resumable (code 75; re-run with --resume).",
                  file=sys.stderr)
            stop["flag"] = True

        signal.signal(signal.SIGINT, handler)
        signal.signal(signal.SIGTERM, handler)
    if not args.quiet:
        print(f"[jax/fleet] serving {sweep.n_scenarios} scenarios in "
              f"{len(sweep.buckets)} bucket(s), rounds<={rounds}, "
              f"target={target if target is not None else 'off'}")
    log = None if args.quiet else (
        lambda msg: print(msg, file=sys.stderr))
    try:
        res = sweep.run(rounds, target=target,
                        checkpoint_dir=args.checkpoint_dir,
                        checkpoint_every=args.checkpoint_every,
                        resume=args.resume,
                        should_stop=lambda: stop["flag"], log=log)
    except CheckpointError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    if not res.results_path:
        for row in res.rows:
            print(json.dumps(row))
    summary = {
        "engine": "fleet",
        "n_scenarios": res.n_scenarios,
        "n_buckets": res.n_buckets,
        "scenarios_served": len(res.rows),
        "converged": sum(1 for r in res.rows if r.get("converged")),
        "wall_s": round(res.wall_s, 4),
    }
    if res.results_path:
        summary["results"] = res.results_path
    if res.interrupted:
        summary["interrupted"] = True
    print(json.dumps(summary))
    if res.interrupted:
        if args.checkpoint_dir and len(res.rows) < res.n_scenarios:
            from p2p_gossipprotocol_tpu import telemetry

            telemetry.event("salvage", kind_detail="fleet",
                            scenarios_done=len(res.rows),
                            n_scenarios=res.n_scenarios)
            telemetry.dump("sigterm_salvage",
                           directory=(cfg.telemetry_dump_dir
                                      or args.checkpoint_dir))
            print(f"[checkpoint] sweep salvaged after {len(res.rows)}/"
                  f"{res.n_scenarios} scenarios — exiting resumable "
                  "(75)", file=sys.stderr)
            return EX_RESUMABLE
        return 1
    return 0


def _run_serve(cfg: NetworkConfig, args) -> int:
    """Run the resident gossip-sim server (serve/): GossipService under
    a ServeServer on the config's socket address.  The preemption
    contract mirrors the sweep driver's: SIGINT/SIGTERM with a
    checkpoint dir salvage every in-flight bucket AND the queue at the
    next chunk boundary and exit 75 (resumable); without one they
    drain gracefully (finish what was admitted, then exit 0)."""
    from p2p_gossipprotocol_tpu.serve.server import ServeServer
    from p2p_gossipprotocol_tpu.serve.service import GossipService
    from p2p_gossipprotocol_tpu.utils.checkpoint import (CheckpointError,
                                                         EX_RESUMABLE)

    log = None if args.quiet else (
        lambda msg: print(msg, file=sys.stderr))
    try:
        service = GossipService(
            cfg, n_peers=args.n_peers,
            rounds=args.rounds or None,
            checkpoint_dir=args.checkpoint_dir,
            results_path=args.sweep_results or None,
            resume=args.resume,
            # replica mode (the fleet router launched us): refresh the
            # salvage snapshot continuously — a SIGKILL runs no
            # handler, so the router recovers from the last periodic
            # manifest instead of losing completed work
            persist_every_s=(1.0 if args.serve_heartbeat
                             and args.checkpoint_dir else 0.0),
            log=log)
    except (CheckpointError, ValueError) as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    server = ServeServer(service, cfg.get_local_ip(),
                         cfg.get_local_port(),
                         wire_format=cfg.wire_format, log=log)
    on_bound = None
    if args.serve_heartbeat:
        on_bound = (lambda port: service.configure_heartbeat(
            args.serve_heartbeat, port, rank=args.serve_rank))
    stop = {"salvage": False}

    def handler(signum, frame):
        if service.checkpoint_dir:
            print("\nReceived signal to terminate — salvaging "
                  "in-flight buckets and the queue at the next chunk "
                  "boundary, then exiting resumable (code 75; re-run "
                  "with --serve --resume).", file=sys.stderr)
            stop["salvage"] = True
        else:
            print("\nReceived signal to terminate — draining "
                  "(no --checkpoint-dir, so in-flight work finishes "
                  "before exit).", file=sys.stderr)
        server._stop.set()

    signal.signal(signal.SIGINT, handler)
    signal.signal(signal.SIGTERM, handler)
    try:
        server.start(on_bound=on_bound)
    except OSError as e:
        print(f"Error: cannot bind {cfg.get_local_ip()}:"
              f"{cfg.get_local_port()} ({e})", file=sys.stderr)
        return 1
    if not args.quiet:
        rebound = (f" (rebound from {server.rebound_from})"
                   if server.rebound_from else "")
        autoscale = (f"autoscale "
                     f"[{service.autoscaler.min_slots},"
                     f"{service.autoscaler.max_slots}]"
                     if service.autoscale else
                     f"{service.slots} slots/bucket")
        print(f"[jax/serve] resident server on {cfg.get_local_ip()}:"
              f"{server.port}{rebound} — {autoscale}, "
              f"<= {service.max_buckets} buckets, "
              f"queue <= {service.scheduler.queue_max}, target "
              f"{service.target:g}, chunk {service.chunk}, "
              f"pipelined wire (window "
              f"{cfg.serve_inflight if cfg.serve_pipeline else 0})")
    server.wait()
    server.stop()
    if stop["salvage"]:
        service.salvage()
        st = service.stats()
        print(json.dumps({"engine": "serve", "salvaged": True, **st}))
        return EX_RESUMABLE
    stats = service.drain()
    print(json.dumps({"engine": "serve", **stats}))
    return 0


def _run_serve_fleet(cfg: NetworkConfig, args) -> int:
    """Run the fault-tolerant serving fleet (serve/router.py):
    ``serve_replicas`` supervised ``--serve`` replica children behind
    the signature-affinity router, fronted by the SAME wire protocol
    on local_ip:local_port.  SIGINT/SIGTERM drain the router
    gracefully (replicas own their per-process salvage)."""
    from p2p_gossipprotocol_tpu.serve.router import RouterService
    from p2p_gossipprotocol_tpu.serve.server import ServeServer

    log = None if args.quiet else (
        lambda msg: print(msg, file=sys.stderr))
    try:
        service = RouterService(cfg, n_peers=args.n_peers,
                                run_dir=args.checkpoint_dir or None,
                                log=log)
    except ValueError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    server = ServeServer(service, cfg.get_local_ip(),
                         cfg.get_local_port(),
                         wire_format=cfg.wire_format, log=log)

    def handler(signum, frame):
        print("\nReceived signal to terminate — draining the fleet "
              "(in-flight work finishes on the replicas before exit).",
              file=sys.stderr)
        server._stop.set()

    signal.signal(signal.SIGINT, handler)
    signal.signal(signal.SIGTERM, handler)
    # form the fleet BEFORE opening the wire: a client must never see
    # a bound port whose submits bounce off a still-forming fleet
    # (RouterService.start() is idempotent — ServeServer re-invoking
    # it is a no-op)
    try:
        service.start()
        service.wait_ready(timeout=300)
    except TimeoutError as e:
        print(f"Error: {e}", file=sys.stderr)
        service.stop()
        return 1
    # federation member mode (round 18): stamp a fleet-kind heartbeat
    # carrying the BOUND wire port + this fleet's name/epoch, so the
    # federation discovers where the fleet listens and judges its
    # liveness — the replica heartbeat contract lifted one level
    on_bound = None
    if args.serve_heartbeat:
        on_bound = (lambda port: service.configure_heartbeat(
            args.serve_heartbeat, port, fleet=args.fleet_name,
            epoch=args.fleet_epoch))
    try:
        server.start(on_bound=on_bound)
    except OSError as e:
        print(f"Error: cannot bind {cfg.get_local_ip()}:"
              f"{cfg.get_local_port()} ({e})", file=sys.stderr)
        service.stop()
        return 1
    if not args.quiet:
        rebound = (f" (rebound from {server.rebound_from})"
                   if server.rebound_from else "")
        print(f"[jax/serve-fleet] router on {cfg.get_local_ip()}:"
              f"{server.port}{rebound} — {service.n_replicas} "
              f"replicas, health deadline {service.health_s:g}s, "
              f"run dir {service.run_dir}")
    try:
        server.wait()
    finally:
        server.stop()
        stats = service.drain(timeout=600)
        service.stop()
    print(json.dumps({"engine": "serve-fleet", **stats}))
    return 0


def _run_federate(cfg: NetworkConfig, args) -> int:
    """Run the global serving federation (serve/federation.py):
    ``federate_fleets`` supervised ``--serve-fleet`` children behind
    the cross-fleet locality router, fronted by the SAME wire protocol
    on local_ip:local_port.  SIGINT/SIGTERM drain the federation
    gracefully (fleets own their per-fleet salvage)."""
    from p2p_gossipprotocol_tpu.serve.federation import FederationService
    from p2p_gossipprotocol_tpu.serve.server import ServeServer

    log = None if args.quiet else (
        lambda msg: print(msg, file=sys.stderr))
    try:
        service = FederationService(cfg, n_peers=args.n_peers,
                                    run_dir=args.checkpoint_dir or None,
                                    log=log)
    except ValueError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    server = ServeServer(service, cfg.get_local_ip(),
                         cfg.get_local_port(),
                         wire_format=cfg.wire_format, log=log)

    def handler(signum, frame):
        print("\nReceived signal to terminate — draining the "
              "federation (in-flight work finishes on the fleets "
              "before exit).", file=sys.stderr)
        server._stop.set()

    signal.signal(signal.SIGINT, handler)
    signal.signal(signal.SIGTERM, handler)
    # form every fleet BEFORE opening the wire — the serve-fleet rule,
    # one level up: a bound port must never front a forming federation
    try:
        service.start()
        service.wait_ready(timeout=600)
    except TimeoutError as e:
        print(f"Error: {e}", file=sys.stderr)
        service.stop()
        return 1
    try:
        server.start()
    except OSError as e:
        print(f"Error: cannot bind {cfg.get_local_ip()}:"
              f"{cfg.get_local_port()} ({e})", file=sys.stderr)
        service.stop()
        return 1
    if not args.quiet:
        rebound = (f" (rebound from {server.rebound_from})"
                   if server.rebound_from else "")
        print(f"[jax/federate] federation on {cfg.get_local_ip()}:"
              f"{server.port}{rebound} — {service.n_fleets} fleet(s) "
              f"x {service.replicas_per_fleet} replica(s), health "
              f"deadline {service.health_s:g}s, run dir "
              f"{service.run_dir}")
    try:
        server.wait()
    finally:
        server.stop()
        stats = service.drain(timeout=900)
        service.stop()
    print(json.dumps({"engine": "federate", **stats}))
    return 0


def _run_supervise(cfg: NetworkConfig, args) -> int:
    """Drive the scenario as a supervised multi-process job
    (runtime/supervisor.py): launch supervise_workers worker
    processes, watch heartbeats against traffic-model deadlines, and
    on a hung/dead worker shrink the mesh to the survivors and resume
    the last elastic checkpoint.  Prints one summary JSON line with
    the recovery history and per-recovery MTTR."""
    from p2p_gossipprotocol_tpu.runtime.supervisor import \
        supervise_from_config

    rounds = args.rounds or cfg.rounds or 64
    res = supervise_from_config(
        cfg, config_path=args.config_file, rounds=rounds,
        n_peers=args.n_peers, checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=args.checkpoint_every, quiet=args.quiet)
    print(json.dumps(res.summary()))
    if res.skipped:
        # environment impossibility (e.g. forced distributed spmd on a
        # backend without multi-process collectives) — the rehearsal's
        # skip convention, not a failure
        return 3
    return 0 if res.ok else 1


def _report_sir(res, *, n_peers, engine, args, metrics_lib,
                clamps=None, graph_backend=None) -> None:
    """Shared SIR census printout + JSONL + summary line (both engines
    return the same SIRResult)."""
    if not args.quiet:
        for i in range(len(res.infected)):
            print(f"round {i + 1:4d}  S={res.susceptible[i]:8d}  "
                  f"I={res.infected[i]:8d}  R={res.recovered[i]:8d}  "
                  f"new={res.new_infections[i]:6d}  "
                  f"live={res.live_peers[i]:8d}")
            if res.infected[i] == 0:
                break
    if args.metrics_jsonl:
        rows = [{
            "susceptible": int(res.susceptible[i]),
            "infected": int(res.infected[i]),
            "recovered": int(res.recovered[i]),
            "new_infections": int(res.new_infections[i]),
            "live_peers": int(res.live_peers[i]),
        } for i in range(len(res.infected))]
        # tmp+rename so a kill mid-dump never leaves a torn metrics
        # table (the write-discipline contract, docs/STATIC_ANALYSIS.md)
        import io

        from p2p_gossipprotocol_tpu.utils.logging import write_atomic

        buf = io.StringIO()
        metrics_lib.emit_jsonl(rows, buf, n_peers=n_peers,
                               mode="sir", engine=engine)
        write_atomic(args.metrics_jsonl, buf.getvalue())
    extinction = res.rounds_to_extinction()
    out = {
        "n_peers": n_peers,
        "mode": "sir",
        "engine": engine,
        "rounds_run": int(len(res.infected)),
        "final_susceptible": int(res.susceptible[-1]),
        "final_infected": int(res.infected[-1]),
        "final_recovered": int(res.recovered[-1]),
        "peak_infected": res.peak_infected,
        "attack_rate": round(res.attack_rate, 6),
        "rounds_to_extinction": extinction,
        "total_new_infections": int(res.new_infections.sum()),
        "wall_s": float(res.wall_s),
    }
    if graph_backend is not None:
        out["graph_backend"] = graph_backend
    if clamps:
        out["clamped"] = clamps
    print(json.dumps(out))


def _report(res, sim, *, n_peers, engine, args, metrics_lib, clamps=None,
            graph_backend=None):
    """Shared per-round printout + JSONL + summary line for both engines
    (they return the same SimResult).  ``rounds_run`` is the number of
    rounds the scan actually executed (fixed-length), and the summary's
    ``rounds_to_<target>`` gives convergence; ``clamped`` records any
    configured value the engine had to reduce; ``graph_backend`` is
    recorded for the edge engine because a seed's topology is
    deterministic within a builder backend, not across them
    (graph.py:from_config — numpy PCG vs native SplitMix64)."""
    if not args.quiet:
        for i in range(len(res.coverage)):
            # frontier/deliveries arrive as float32 from the aligned
            # engines (the exact popcount pair combines to float so
            # totals past 2^31 bits don't wrap) — render as ints
            print(f"round {i + 1:4d}  coverage={res.coverage[i]:.4f}  "
                  f"frontier={int(res.frontier_size[i]):8d}  "
                  f"live={int(res.live_peers[i]):8d}  "
                  f"evictions={int(res.evictions[i]):6d}")
            if res.coverage[i] >= 0.999999 and res.frontier_size[i] == 0:
                break
    if args.metrics_jsonl:
        import io

        from p2p_gossipprotocol_tpu.utils.logging import write_atomic

        buf = io.StringIO()
        metrics_lib.emit_jsonl(metrics_lib.rows_from_result(res), buf,
                               n_peers=n_peers, mode=sim.mode,
                               engine=engine)
        write_atomic(args.metrics_jsonl, buf.getvalue())
    summary = metrics_lib.summarize(res, args.target_coverage)
    summary.pop("rounds", None)   # identical to rounds_run below
    out = {
        "n_peers": n_peers,
        "n_msgs": sim.n_msgs,
        "mode": sim.mode,
        "engine": engine,
        "rounds_run": int(len(res.coverage)),
        **summary,
    }
    if graph_backend is not None:
        out["graph_backend"] = graph_backend
    if clamps:
        out["clamped"] = clamps
    print(json.dumps(out))


def _run_socket(cfg: NetworkConfig, args) -> int:
    stop = {"flag": False}

    def handler(signum, frame):  # main.cpp:14-22
        print("\nReceived signal to terminate. Shutting down...",
              file=sys.stderr)
        stop["flag"] = True

    signal.signal(signal.SIGINT, handler)
    signal.signal(signal.SIGTERM, handler)

    if args.role == "seed":
        from p2p_gossipprotocol_tpu.seed import SeedNode

        node = SeedNode(cfg.get_local_ip(), cfg.get_local_port(),
                        wire_format=cfg.wire_format)
        node.start()
    else:
        from p2p_gossipprotocol_tpu.wrapper import Peer

        node = Peer(args.config_file, config=cfg)
        node.start()

    try:
        while not stop["flag"] and node.is_running():
            time.sleep(0.1)  # main.cpp:59-61
    finally:
        node.stop()
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print_usage("peer_network")
        return 1
    args = build_parser().parse_args(argv)
    try:
        cfg = NetworkConfig(args.config_file)
    except ConfigError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1

    # telemetry plane: configure the process recorder from the config's
    # telemetry_* keys (--telemetry / GOSSIP_TELEMETRY=1 force-enable),
    # and chain the crash-dump hook so an uncaught exception leaves a
    # flight-recorder dump — every post-mortem ships its own trace
    from p2p_gossipprotocol_tpu import telemetry

    rec = telemetry.configure_from_config(cfg, force=args.telemetry)
    if rec.enabled or rec.dump_dir:
        rec.install_crash_dump(
            directory=rec.dump_dir or cfg.checkpoint_dir or None)

    if args.backend:
        cfg.backend = args.backend
    if args.local_ip:
        cfg.local_ip = args.local_ip
    if args.local_port:
        cfg.local_port = args.local_port
    if args.mode:
        cfg.mode = args.mode
    if args.graph:
        cfg.graph = args.graph
    if args.wire_format:
        cfg.wire_format = args.wire_format
    if args.engine:
        cfg.engine = args.engine
    if args.graph_file:
        # --graph-file implies the realgraph engine unless a flag or
        # config key already picked one that consumes it (the fleet
        # spec layer routes graph_file lines to realgraph itself)
        cfg.graph_file = args.graph_file
        if not args.engine and cfg.engine == "edges":
            cfg.engine = "realgraph"
    if args.sweep:
        # --sweep implies the fleet engine: the spec file IS the sweep
        cfg.sweep_file = args.sweep
        cfg.engine = "fleet"
    if args.sweep_results:
        cfg.sweep_results = args.sweep_results
    if cfg.engine == "fleet" and cfg.backend != "jax":
        print("Error: engine=fleet is a jax-backend feature (the "
              "socket runtime is one real peer process)",
              file=sys.stderr)
        return 1
    args.engine = cfg.engine
    if args.fault_plan:
        from p2p_gossipprotocol_tpu import faults as faults_lib

        try:
            plan = faults_lib.apply_spec_to_config(cfg, args.fault_plan)
        except ValueError as e:
            print(f"Error: bad --fault-plan: {e}", file=sys.stderr)
            return 1
        if not args.quiet:
            print(f"[faults] {plan.to_spec() or 'none'}", file=sys.stderr)
    # flags override the config keys; absent flags fall back to them, so
    # a config file alone selects any engine (same table as the facade)
    if args.mesh_devices is None:
        args.mesh_devices = cfg.mesh_devices
    if args.msg_shards is None:
        args.msg_shards = cfg.msg_shards
    if cfg.backend != "jax" and (args.mesh_devices > 1
                                 or args.msg_shards > 1):
        # fail fast, not a silent socket run the user believes is sharded
        print("Error: --mesh-devices/--msg-shards are jax-backend "
              "features (the socket runtime is one real peer process)",
              file=sys.stderr)
        return 1
    # checkpoint flags fall back to the config keys (same rule as the
    # mesh flags above), so a config file alone gets elastic resume
    if args.checkpoint_every == 0 and cfg.checkpoint_every > 0:
        args.checkpoint_every = cfg.checkpoint_every
    if args.checkpoint_dir is None and cfg.checkpoint_dir:
        args.checkpoint_dir = cfg.checkpoint_dir
    if not args.resume and cfg.checkpoint_resume:
        args.resume = True
    if (args.checkpoint_every > 0 or args.resume) \
            and not args.checkpoint_dir:
        print("Error: --checkpoint-every/--resume need --checkpoint-dir",
              file=sys.stderr)
        return 1
    if args.checkpoint_dir and cfg.backend != "jax":
        print("Error: checkpointing is a jax-backend feature (the socket "
              "runtime is the reference's in-memory-only model)",
              file=sys.stderr)
        return 1

    if args.federate or args.serve_fleet or args.serve or cfg.serve \
            or getattr(cfg, "federate", 0):
        # resident server (fleet, or the fleet-of-fleets federation):
        # the process stays up serving; the one-shot path never runs
        what = ("--serve-fleet" if args.serve_fleet
                else "--serve" if args.serve
                else "--federate" if args.federate
                or getattr(cfg, "federate", 0)
                else "--serve")
        if cfg.backend != "jax":
            print(f"Error: {what} is a jax-backend feature (the "
                  "socket runtime is one real peer process; the serve "
                  "protocol shares its wire, not its role)",
                  file=sys.stderr)
            return 1
        if cfg.mode == "sir":
            print(f"Error: {what} serves the gossip modes (the fleet "
                  "engine batches push/pull/pushpull scenarios)",
                  file=sys.stderr)
            return 1
        # explicit child-role flags FIRST: the federation spawns
        # --serve-fleet children and the router spawns --serve children
        # from the SAME config file — a `federate`/`serve` config key
        # must never re-dispatch a child back into its parent's role
        # (fork recursion)
        if args.serve_fleet:
            return _run_serve_fleet(cfg, args)
        if args.serve:
            return _run_serve(cfg, args)
        if args.federate or getattr(cfg, "federate", 0):
            return _run_federate(cfg, args)
        return _run_serve(cfg, args)

    if args.supervise or cfg.supervise:
        # supervised multi-process run: the supervisor owns the worker
        # processes; this process never initializes jax (it must stay
        # killable while a worker wedges in backend init)
        if cfg.backend != "jax":
            print("Error: --supervise is a jax-backend feature (the "
                  "socket runtime is one real peer process)",
                  file=sys.stderr)
            return 1
        if cfg.engine != "aligned":
            print("Error: --supervise drives the aligned-sharded "
                  "engine family (set engine=aligned) — its layouts "
                  "share one RNG schedule, which is what makes "
                  "shrink-to-survivors resume bitwise "
                  "(docs/ROBUSTNESS.md)", file=sys.stderr)
            return 1
        if cfg.mode == "sir":
            print("Error: --supervise covers the gossip modes",
                  file=sys.stderr)
            return 1
        if not args.checkpoint_dir and not args.quiet:
            print("Warning: --supervise without --checkpoint-dir — a "
                  "recovery restarts the shrunk job from round 0 "
                  "instead of resuming the last checkpoint",
                  file=sys.stderr)
        return _run_supervise(cfg, args)

    if not args.quiet:
        print(cfg.to_string())  # main.cpp:48

    if cfg.backend == "jax" and args.role == "peer":
        return _run_jax(cfg, args)
    return _run_socket(cfg, args)


if __name__ == "__main__":
    sys.exit(main())

"""CLI entry point: ``peer_network <config_file>``.

Preserves the reference's invocation exactly (main.cpp:29-34: one
positional config-file argument, usage message on error, SIGINT/SIGTERM
graceful shutdown, config printed at startup) and adds what it lacks:

* ``--backend {jax,socket}`` — TPU simulation vs n-terminal socket mode;
* ``--role {peer,seed}``     — a real entry point for the seed role the
  reference defined but never wired up (SURVEY §3.5);
* ``--n-peers/--rounds/--mode/...`` — simulation overrides;
* a machine-readable result line (JSON) after a jax-backend run.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import time

from p2p_gossipprotocol_tpu.config import ConfigError, NetworkConfig


def print_usage(prog: str) -> None:
    # Text shape mirrors printUsage (main.cpp:24-27).
    print(f"Usage: {prog} <config_file>", file=sys.stderr)
    print("  config_file: Path to network configuration file",
          file=sys.stderr)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="peer_network", add_help=True,
        description="TPU-native gossip network "
                    "(capabilities of PareenShah27/P2P-GossipProtocol)")
    p.add_argument("config_file", help="network configuration file")
    p.add_argument("--backend", choices=["jax", "socket"], default=None,
                   help="override config backend")
    p.add_argument("--role", choices=["peer", "seed"], default="peer",
                   help="socket mode: run a peer or a seed server")
    p.add_argument("--n-peers", type=int, default=None,
                   help="jax mode: simulated peer count")
    p.add_argument("--rounds", type=int, default=None,
                   help="jax mode: rounds to simulate")
    p.add_argument("--mode", choices=["push", "pull", "pushpull"],
                   default=None, help="gossip mode override")
    p.add_argument("--target-coverage", type=float, default=0.99)
    p.add_argument("--local-ip", default=None)
    p.add_argument("--local-port", type=int, default=None)
    p.add_argument("--quiet", action="store_true")
    return p


def _run_jax(cfg: NetworkConfig, args) -> int:
    from p2p_gossipprotocol_tpu.sim import Simulator

    sim = Simulator.from_config(cfg, n_peers=args.n_peers)
    rounds = args.rounds or cfg.rounds or 64
    if not args.quiet:
        print(f"[jax] simulating {sim.topo.n_peers} peers, "
              f"{sim.n_msgs} messages, mode={sim.mode}, "
              f"{int(sim.topo.n_edges())} edges")
    res = sim.run(rounds)
    r99 = res.rounds_to(args.target_coverage)
    if not args.quiet:
        for i in range(len(res.coverage)):
            print(f"round {i + 1:4d}  coverage={res.coverage[i]:.4f}  "
                  f"frontier={res.frontier_size[i]:8d}  "
                  f"live={res.live_peers[i]:8d}  "
                  f"evictions={res.evictions[i]:6d}")
            if res.coverage[i] >= 0.999999 and res.frontier_size[i] == 0:
                break
    print(json.dumps({
        "n_peers": sim.topo.n_peers,
        "n_msgs": sim.n_msgs,
        "mode": sim.mode,
        "rounds_run": rounds,
        "final_coverage": float(res.coverage[-1]),
        f"rounds_to_{args.target_coverage:g}": r99,
        "total_deliveries": res.total_deliveries,
        "wall_s": round(res.wall_s, 4),
    }))
    return 0


def _run_socket(cfg: NetworkConfig, args) -> int:
    stop = {"flag": False}

    def handler(signum, frame):  # main.cpp:14-22
        print("\nReceived signal to terminate. Shutting down...",
              file=sys.stderr)
        stop["flag"] = True

    signal.signal(signal.SIGINT, handler)
    signal.signal(signal.SIGTERM, handler)

    if args.role == "seed":
        from p2p_gossipprotocol_tpu.seed import SeedNode

        node = SeedNode(cfg.get_local_ip(), cfg.get_local_port())
        node.start()
    else:
        from p2p_gossipprotocol_tpu.wrapper import Peer

        node = Peer(args.config_file, config=cfg)
        node.start()

    try:
        while not stop["flag"] and node.is_running():
            time.sleep(0.1)  # main.cpp:59-61
    finally:
        node.stop()
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print_usage("peer_network")
        return 1
    args = build_parser().parse_args(argv)
    try:
        cfg = NetworkConfig(args.config_file)
    except ConfigError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1

    if args.backend:
        cfg.backend = args.backend
    if args.local_ip:
        cfg.local_ip = args.local_ip
    if args.local_port:
        cfg.local_port = args.local_port
    if args.mode:
        cfg.mode = args.mode

    if not args.quiet:
        print(cfg.to_string())  # main.cpp:48

    if cfg.backend == "jax" and args.role == "peer":
        return _run_jax(cfg, args)
    return _run_socket(cfg, args)


if __name__ == "__main__":
    sys.exit(main())

"""CLI entry point: ``peer_network <config_file>``.

Preserves the reference's invocation exactly (main.cpp:29-34: one
positional config-file argument, usage message on error, SIGINT/SIGTERM
graceful shutdown, config printed at startup) and adds what it lacks:

* ``--backend {jax,socket}`` — TPU simulation vs n-terminal socket mode;
* ``--role {peer,seed}``     — a real entry point for the seed role the
  reference defined but never wired up (SURVEY §3.5);
* ``--n-peers/--rounds/--mode/...`` — simulation overrides;
* a machine-readable result line (JSON) after a jax-backend run.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import time

from p2p_gossipprotocol_tpu.config import ConfigError, NetworkConfig


def print_usage(prog: str) -> None:
    # Text shape mirrors printUsage (main.cpp:24-27).
    print(f"Usage: {prog} <config_file>", file=sys.stderr)
    print("  config_file: Path to network configuration file",
          file=sys.stderr)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="peer_network", add_help=True,
        description="TPU-native gossip network "
                    "(capabilities of PareenShah27/P2P-GossipProtocol)")
    p.add_argument("config_file", help="network configuration file")
    p.add_argument("--backend", choices=["jax", "socket"], default=None,
                   help="override config backend")
    p.add_argument("--role", choices=["peer", "seed"], default="peer",
                   help="socket mode: run a peer or a seed server")
    p.add_argument("--n-peers", type=int, default=None,
                   help="jax mode: simulated peer count")
    p.add_argument("--rounds", type=int, default=None,
                   help="jax mode: rounds to simulate")
    p.add_argument("--mode", choices=["push", "pull", "pushpull", "sir"],
                   default=None,
                   help="gossip mode override (sir = epidemic model)")
    p.add_argument("--graph",
                   choices=["reference", "er", "ba", "powerlaw"],
                   default=None,
                   help="jax mode: overlay model override (same as the "
                        "graph= config key)")
    p.add_argument("--engine", choices=["edges", "aligned"],
                   default=None,
                   help="jax mode: exact edge-list engine, or the "
                        "hardware-aligned pallas engine (1M+ peers); "
                        "default: the config's engine= key (edges)")
    p.add_argument("--mesh-devices", type=int, default=0, metavar="N",
                   help="jax mode: shard the peer axis over an N-device "
                        "mesh (ShardedSimulator / "
                        "AlignedShardedSimulator); 0 = single device")
    p.add_argument("--msg-shards", type=int, default=0, metavar="M",
                   help="with --engine aligned and --mesh-devices N: "
                        "also shard the message planes, as an "
                        "M x (N/M) (msgs x peers) 2-D mesh "
                        "(Aligned2DShardedSimulator); 0 = peers only")
    p.add_argument("--target-coverage", type=float, default=0.99)
    p.add_argument("--local-ip", default=None)
    p.add_argument("--local-port", type=int, default=None)
    p.add_argument("--wire-format", choices=["json", "framed"],
                   default=None,
                   help="socket mode: reference-compatible unframed JSON "
                        "or length-framed (same as the wire_format= "
                        "config key)")
    p.add_argument("--checkpoint-every", type=int, default=0, metavar="N",
                   help="jax mode: checkpoint the full simulation state "
                        "every N rounds (orbax) into --checkpoint-dir")
    p.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                   help="where checkpoints live (required with "
                        "--checkpoint-every / --resume)")
    p.add_argument("--resume", action="store_true",
                   help="jax mode: continue from the checkpoint in "
                        "--checkpoint-dir; the completed run's summary "
                        "is identical to an uninterrupted one")
    p.add_argument("--metrics-jsonl", default=None, metavar="PATH",
                   help="write per-round metrics as JSONL")
    p.add_argument("--profile-dir", default=None, metavar="DIR",
                   help="jax.profiler trace directory for the run")
    p.add_argument("--quiet", action="store_true")
    return p


def _run_sim(sim, rounds, args):
    """sim.run(rounds), optionally through the checkpoint runner (the
    CLI face of utils.checkpoint.run_with_checkpoints: kill a run, pass
    --resume, get the summary an uninterrupted run would print)."""
    if args.checkpoint_every > 0 or args.resume:
        from p2p_gossipprotocol_tpu.utils.checkpoint import \
            run_with_checkpoints

        return run_with_checkpoints(
            sim, rounds, every=args.checkpoint_every or rounds,
            directory=args.checkpoint_dir, resume=args.resume)
    return sim.run(rounds)


def _run_jax(cfg: NetworkConfig, args) -> int:
    from p2p_gossipprotocol_tpu.utils import metrics as metrics_lib

    rounds = args.rounds or cfg.rounds or 64
    if args.mesh_devices > 1:
        # Fail fast BEFORE topology construction — building a 10M-peer
        # overlay only to learn the mesh doesn't exist wastes tens of
        # seconds and GBs of host RAM.
        import jax

        have = len(jax.devices())
        if args.mesh_devices > have:
            print(f"Error: requested {args.mesh_devices} devices, "
                  f"have {have}", file=sys.stderr)
            return 1
    with metrics_lib.profile(args.profile_dir):
        if cfg.mode == "sir":
            if args.engine == "aligned":
                return _run_jax_sir_aligned(cfg, args, rounds, metrics_lib)
            if args.mesh_devices > 1:
                print("Error: --mesh-devices with the SIR model needs "
                      "--engine aligned (the edges SIR engine is "
                      "single-device)", file=sys.stderr)
                return 1
            return _run_jax_sir(cfg, args, rounds, metrics_lib)
        if args.engine == "aligned":
            return _run_jax_aligned(cfg, args, rounds, metrics_lib)

        from p2p_gossipprotocol_tpu.sim import Simulator

        sim = Simulator.from_config(cfg, n_peers=args.n_peers)
        engine = "edges"
        if args.mesh_devices > 1:
            # Same scenario, sharded over the mesh: from_config resolved
            # every knob (junk columns, churn, strikes); lift them onto
            # the drop-in multi-chip simulator.
            from p2p_gossipprotocol_tpu.parallel import (ShardedSimulator,
                                                         make_mesh)

            try:
                sim = ShardedSimulator(
                    topo=sim.topo, mesh=make_mesh(args.mesh_devices),
                    n_msgs=sim.n_msgs, mode=sim.mode, fanout=sim.fanout,
                    churn=sim.churn,
                    byzantine_fraction=sim.byzantine_fraction,
                    n_honest_msgs=sim.n_honest_msgs,
                    max_strikes=sim.max_strikes, seed=sim.seed)
            except ValueError as e:
                print(f"Error: {e}", file=sys.stderr)
                return 1
            engine = f"edges-sharded-{args.mesh_devices}"
        if not args.quiet:
            print(f"[jax] simulating {sim.topo.n_peers} peers, "
                  f"{sim.n_msgs} messages, mode={sim.mode}, "
                  f"{int(sim.topo.n_edges())} edges, engine={engine}")
        res = _run_sim(sim, rounds, args)
    _report(res, sim, n_peers=sim.topo.n_peers, engine=engine,
            args=args, metrics_lib=metrics_lib,
            graph_backend=cfg.graph_backend)
    return 0


def _run_jax_sir(cfg: NetworkConfig, args, rounds, metrics_lib) -> int:
    """Drive the SIR epidemic model (BASELINE config 3: BA-100k) through
    the same report path as the gossip engines: per-round census lines,
    optional JSONL, one summary JSON line with the epidemic-curve fields
    (S/I/R, peak_infected, attack_rate)."""
    from p2p_gossipprotocol_tpu.sim import SIRSimulator

    sim = SIRSimulator.from_config(cfg, n_peers=args.n_peers)
    if not args.quiet:
        print(f"[jax/sir] simulating {sim.topo.n_peers} peers, "
              f"beta={sim.beta:g}, gamma={sim.gamma:g}, "
              f"{int(sim.topo.n_edges())} edges")
    res = _run_sim(sim, rounds, args)
    _report_sir(res, n_peers=sim.topo.n_peers, engine="edges", args=args,
                metrics_lib=metrics_lib, graph_backend=cfg.graph_backend)
    return 0


def _run_jax_sir_aligned(cfg: NetworkConfig, args, rounds,
                         metrics_lib) -> int:
    """BASELINE config 3 on the scale path: the aligned overlay's SIR
    engine (aligned_sir.py), single-chip or sharded over --mesh-devices."""
    from p2p_gossipprotocol_tpu.aligned_sir import AlignedSIRSimulator

    clamps: list[str] = []
    n_shards = max(1, args.mesh_devices)
    try:
        sim = AlignedSIRSimulator.from_config(cfg, n_peers=args.n_peers,
                                              n_shards=n_shards,
                                              clamps=clamps)
    except ValueError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    for c in clamps:
        print(f"Warning: --engine aligned clamped {c}", file=sys.stderr)
    engine = "aligned"
    if n_shards > 1:
        from p2p_gossipprotocol_tpu.parallel import (
            AlignedShardedSIRSimulator, make_mesh)

        try:
            sim = AlignedShardedSIRSimulator(
                mesh=make_mesh(n_shards), topo=sim.topo, beta=sim.beta,
                gamma=sim.gamma, n_seeds=sim.n_seeds, churn=sim.churn,
                seed=sim.seed)
        except ValueError as e:
            print(f"Error: {e}", file=sys.stderr)
            return 1
        engine = f"aligned-sharded-{n_shards}"
    n = sim.topo.n_peers
    if not args.quiet:
        print(f"[jax/sir] simulating {n} peers, beta={cfg.sir_beta:g}, "
              f"gamma={cfg.sir_gamma:g}, {sim.topo.n_slots} slots/peer, "
              f"engine={engine}")
    res = _run_sim(sim, rounds, args)
    _report_sir(res, n_peers=n, engine=engine, args=args,
                metrics_lib=metrics_lib, clamps=clamps)
    return 0


def _report_sir(res, *, n_peers, engine, args, metrics_lib,
                clamps=None, graph_backend=None) -> None:
    """Shared SIR census printout + JSONL + summary line (both engines
    return the same SIRResult)."""
    if not args.quiet:
        for i in range(len(res.infected)):
            print(f"round {i + 1:4d}  S={res.susceptible[i]:8d}  "
                  f"I={res.infected[i]:8d}  R={res.recovered[i]:8d}  "
                  f"new={res.new_infections[i]:6d}  "
                  f"live={res.live_peers[i]:8d}")
            if res.infected[i] == 0:
                break
    if args.metrics_jsonl:
        rows = [{
            "susceptible": int(res.susceptible[i]),
            "infected": int(res.infected[i]),
            "recovered": int(res.recovered[i]),
            "new_infections": int(res.new_infections[i]),
            "live_peers": int(res.live_peers[i]),
        } for i in range(len(res.infected))]
        with open(args.metrics_jsonl, "w") as fp:
            metrics_lib.emit_jsonl(rows, fp, n_peers=n_peers,
                                   mode="sir", engine=engine)
    extinction = res.rounds_to_extinction()
    out = {
        "n_peers": n_peers,
        "mode": "sir",
        "engine": engine,
        "rounds_run": int(len(res.infected)),
        "final_susceptible": int(res.susceptible[-1]),
        "final_infected": int(res.infected[-1]),
        "final_recovered": int(res.recovered[-1]),
        "peak_infected": res.peak_infected,
        "attack_rate": round(res.attack_rate, 6),
        "rounds_to_extinction": extinction,
        "total_new_infections": int(res.new_infections.sum()),
        "wall_s": float(res.wall_s),
    }
    if graph_backend is not None:
        out["graph_backend"] = graph_backend
    if clamps:
        out["clamped"] = clamps
    print(json.dumps(out))


def _run_jax_aligned(cfg: NetworkConfig, args, rounds, metrics_lib) -> int:
    from p2p_gossipprotocol_tpu.aligned import AlignedSimulator

    clamps: list[str] = []
    n_shards = max(1, args.mesh_devices)
    try:
        # from_config owns every engine ceiling (overlay family, 2048-
        # message cap, byzantine junk budget, int8 strike range, VMEM
        # row-block budget) — shared with the wrapper facade.
        sim = AlignedSimulator.from_config(cfg, n_peers=args.n_peers,
                                           n_shards=n_shards,
                                           clamps=clamps)
    except ValueError as e:
        # fail cleanly like the mode/fanout checks instead of leaking a
        # traceback (values --engine edges accepts, e.g. max_missed_pings
        # outside the int8 strike range)
        print(f"Error: {e}", file=sys.stderr)
        return 1
    for c in clamps:
        print(f"Warning: --engine aligned clamped {c}", file=sys.stderr)
    engine = "aligned"
    if n_shards > 1:
        lifted = dict(
            topo=sim.topo, n_msgs=sim.n_msgs, mode=sim.mode,
            fanout=sim.fanout, churn=sim.churn,
            byzantine_fraction=sim.byzantine_fraction,
            n_honest_msgs=sim.n_honest_msgs,
            max_strikes=sim.max_strikes,
            liveness_every=sim.liveness_every, seed=sim.seed)
        try:
            if args.msg_shards > 1:
                # 2-D mesh: message planes x peer rows (the SP analogue,
                # parallel/aligned_2d.py)
                from p2p_gossipprotocol_tpu.parallel import (
                    Aligned2DShardedSimulator, make_mesh_2d)

                if n_shards % args.msg_shards:
                    print(f"Error: --msg-shards {args.msg_shards} does "
                          f"not divide --mesh-devices {n_shards}",
                          file=sys.stderr)
                    return 1
                peer_shards = n_shards // args.msg_shards
                sim = Aligned2DShardedSimulator(
                    mesh=make_mesh_2d(args.msg_shards, peer_shards),
                    **lifted)
                engine = (f"aligned-2d-{args.msg_shards}x{peer_shards}")
            else:
                from p2p_gossipprotocol_tpu.parallel import (
                    AlignedShardedSimulator, make_mesh)

                sim = AlignedShardedSimulator(
                    mesh=make_mesh(n_shards), **lifted)
                engine = f"aligned-sharded-{n_shards}"
        except ValueError as e:
            print(f"Error: {e}", file=sys.stderr)
            return 1
    n = sim.topo.n_peers
    if not args.quiet:
        print(f"[jax/aligned] simulating {n} peers, {sim.n_msgs} "
              f"messages, mode={sim.mode}, {sim.topo.n_slots} slots/peer, "
              f"churn={cfg.churn_rate:g}, "
              f"byzantine={cfg.byzantine_fraction:g}, engine={engine}")
    res = _run_sim(sim, rounds, args)
    _report(res, sim, n_peers=n, engine=engine,
            args=args, metrics_lib=metrics_lib, clamps=clamps)
    return 0


def _report(res, sim, *, n_peers, engine, args, metrics_lib, clamps=None,
            graph_backend=None):
    """Shared per-round printout + JSONL + summary line for both engines
    (they return the same SimResult).  ``rounds_run`` is the number of
    rounds the scan actually executed (fixed-length), and the summary's
    ``rounds_to_<target>`` gives convergence; ``clamped`` records any
    configured value the engine had to reduce; ``graph_backend`` is
    recorded for the edge engine because a seed's topology is
    deterministic within a builder backend, not across them
    (graph.py:from_config — numpy PCG vs native SplitMix64)."""
    if not args.quiet:
        for i in range(len(res.coverage)):
            print(f"round {i + 1:4d}  coverage={res.coverage[i]:.4f}  "
                  f"frontier={res.frontier_size[i]:8d}  "
                  f"live={res.live_peers[i]:8d}  "
                  f"evictions={res.evictions[i]:6d}")
            if res.coverage[i] >= 0.999999 and res.frontier_size[i] == 0:
                break
    if args.metrics_jsonl:
        with open(args.metrics_jsonl, "w") as fp:
            metrics_lib.emit_jsonl(metrics_lib.rows_from_result(res), fp,
                                   n_peers=n_peers, mode=sim.mode,
                                   engine=engine)
    summary = metrics_lib.summarize(res, args.target_coverage)
    summary.pop("rounds", None)   # identical to rounds_run below
    out = {
        "n_peers": n_peers,
        "n_msgs": sim.n_msgs,
        "mode": sim.mode,
        "engine": engine,
        "rounds_run": int(len(res.coverage)),
        **summary,
    }
    if graph_backend is not None:
        out["graph_backend"] = graph_backend
    if clamps:
        out["clamped"] = clamps
    print(json.dumps(out))


def _run_socket(cfg: NetworkConfig, args) -> int:
    stop = {"flag": False}

    def handler(signum, frame):  # main.cpp:14-22
        print("\nReceived signal to terminate. Shutting down...",
              file=sys.stderr)
        stop["flag"] = True

    signal.signal(signal.SIGINT, handler)
    signal.signal(signal.SIGTERM, handler)

    if args.role == "seed":
        from p2p_gossipprotocol_tpu.seed import SeedNode

        node = SeedNode(cfg.get_local_ip(), cfg.get_local_port(),
                        wire_format=cfg.wire_format)
        node.start()
    else:
        from p2p_gossipprotocol_tpu.wrapper import Peer

        node = Peer(args.config_file, config=cfg)
        node.start()

    try:
        while not stop["flag"] and node.is_running():
            time.sleep(0.1)  # main.cpp:59-61
    finally:
        node.stop()
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print_usage("peer_network")
        return 1
    args = build_parser().parse_args(argv)
    try:
        cfg = NetworkConfig(args.config_file)
    except ConfigError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1

    if args.backend:
        cfg.backend = args.backend
    if args.local_ip:
        cfg.local_ip = args.local_ip
    if args.local_port:
        cfg.local_port = args.local_port
    if args.mode:
        cfg.mode = args.mode
    if args.graph:
        cfg.graph = args.graph
    if args.wire_format:
        cfg.wire_format = args.wire_format
    if args.engine:
        cfg.engine = args.engine
    args.engine = cfg.engine

    if args.msg_shards > 1 and (cfg.engine != "aligned"
                                or args.mesh_devices <= 1
                                or cfg.mode == "sir"):
        print("Error: --msg-shards needs --engine aligned, "
              "--mesh-devices > 1, and a gossip mode (the 2-D mesh "
              "shards the bit-packed message planes)", file=sys.stderr)
        return 1
    if (args.checkpoint_every > 0 or args.resume) \
            and not args.checkpoint_dir:
        print("Error: --checkpoint-every/--resume need --checkpoint-dir",
              file=sys.stderr)
        return 1
    if args.checkpoint_dir and cfg.backend != "jax":
        print("Error: checkpointing is a jax-backend feature (the socket "
              "runtime is the reference's in-memory-only model)",
              file=sys.stderr)
        return 1

    if not args.quiet:
        print(cfg.to_string())  # main.cpp:48

    if cfg.backend == "jax" and args.role == "peer":
        return _run_jax(cfg, args)
    return _run_socket(cfg, args)


if __name__ == "__main__":
    sys.exit(main())

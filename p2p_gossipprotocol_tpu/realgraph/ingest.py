"""Streaming edge-list ingest -> the canonical CSR graph artifact.

Real graphs arrive as text edge lists (whitespace/SNAP or CSV, with
``#``/``%`` comment lines and an optional CSV header).  The loader
reads the file in bounded byte chunks — a 100M+-edge file never
materializes in host RAM as text; peak footprint is the compact int64
edge arrays themselves — and resolves the edges through
``graph._pad_and_build``, the SAME canonicalization every jax engine's
overlay takes (self-loop/out-of-range filter, stable src sort, 1024
padding).  The artifact is therefore bitwise the topology the edges
engine would build from the same list, which is what makes the
realgraph==edges parity contract checkable at all.

On disk an artifact is a directory of ``.npy`` leaves (src, dst,
edge_mask, row_ptr, deg_in, deg_out) plus ``graph_manifest.json``,
written tmp+rename LAST with a CRC per leaf — the
``utils/checkpoint.py`` atomic+CRC discipline, same named errors: a
torn write leaves the previous manifest (or none) in place, a
corrupted leaf is a :class:`CorruptCheckpoint` naming the leaf, never
a silently different graph.

The seeded RMAT generator (:func:`rmat_edges`) gives tests and benches
power-law graphs with realistic skew at any scale, deterministically.
"""

from __future__ import annotations

import json
import os

import numpy as np

from p2p_gossipprotocol_tpu.utils.checkpoint import (CheckpointError,
                                                     CorruptCheckpoint,
                                                     _crc_entry,
                                                     _write_atomic,
                                                     read_manifest)

#: artifact manifest schema (independent of the checkpoint schema —
#: a graph artifact is immutable input data, not run state)
ARTIFACT_SCHEMA = 1

#: manifest filename inside an artifact directory
MANIFEST = "graph_manifest.json"

#: the array leaves an artifact persists, in manifest order
ARTIFACT_LEAVES = ("src", "dst", "edge_mask", "row_ptr", "deg_in",
                   "deg_out")

#: default streaming read size (32 MiB of text per chunk)
CHUNK_BYTES = 32 << 20


class GraphFormatError(CheckpointError):
    """An edge-list file the parser cannot read, with the line number
    and the offending text — a malformed line is a named error at
    ingest, never a silently dropped edge."""


# ---------------------------------------------------------------------
# Streaming text parsing.

def _detect_format(line: str) -> str:
    return "csv" if "," in line else "ws"


def _parse_chunk(text: str, fmt: str, lineno0: int, first: list
                 ) -> np.ndarray:
    """Parse one decoded chunk into an int64 ``[k, 2]`` edge array.
    ``first`` is a one-element mutable flag: the first data line of a
    CSV file may be a header and is skipped on parse failure (once)."""
    rows: list = []
    sep = "," if fmt == "csv" else None
    for off, raw in enumerate(text.split("\n")):
        line = raw.strip()
        if not line or line[0] in "#%":
            continue
        parts = line.split(sep)
        if len(parts) < 2:
            raise GraphFormatError(
                f"edge-list line {lineno0 + off + 1}: expected "
                f"'src dst', got {line!r}")
        try:
            rows.append((int(parts[0]), int(parts[1])))
        except ValueError:
            if first[0]:
                first[0] = False     # a CSV header line, once
                continue
            raise GraphFormatError(
                f"edge-list line {lineno0 + off + 1}: non-integer "
                f"endpoint in {line!r}")
        first[0] = False
    if not rows:
        return np.zeros((0, 2), np.int64)
    return np.asarray(rows, np.int64)


def iter_edge_chunks(path: str, fmt: str = "auto",
                     chunk_bytes: int = CHUNK_BYTES):
    """Yield ``int64[k, 2]`` edge arrays from a text edge list, reading
    at most ``chunk_bytes`` of file at a time.  ``fmt``: ``ws`` /
    ``snap`` (whitespace columns, ``#``/``%`` comments — SNAP is the
    whitespace dialect), ``csv``, or ``auto`` (sniffed from the first
    data line)."""
    if fmt not in ("auto", "ws", "csv", "snap"):
        raise GraphFormatError(
            f"unknown edge-list format {fmt!r} (auto/ws/csv/snap)")
    eff = "ws" if fmt == "snap" else fmt
    first = [True]
    lineno = 0
    rem = b""
    try:
        fp = open(path, "rb")
    except OSError as e:
        raise GraphFormatError(f"unable to open edge list {path!r} "
                               f"({e})") from e
    with fp:
        while True:
            buf = fp.read(chunk_bytes)
            if not buf:
                break
            buf = rem + buf
            nl = buf.rfind(b"\n")
            if nl < 0:
                rem = buf
                continue
            text, rem = buf[:nl], buf[nl + 1:]
            decoded = text.decode("utf-8", errors="strict")
            if eff == "auto":
                probe = next((ln for ln in decoded.split("\n")
                              if ln.strip() and ln.strip()[0]
                              not in "#%"), None)
                if probe is not None:
                    eff = _detect_format(probe)
            if eff != "auto":
                yield _parse_chunk(decoded, eff, lineno, first)
            lineno += decoded.count("\n") + 1
        if rem:
            decoded = rem.decode("utf-8", errors="strict")
            if eff == "auto":
                eff = _detect_format(decoded)
            yield _parse_chunk(decoded, eff, lineno, first)


# ---------------------------------------------------------------------
# RMAT generator (seeded, vectorized — power-law degree skew).

def rmat_edges(n_log2: int, n_edges: int, seed: int = 0,
               a: float = 0.57, b: float = 0.19, c: float = 0.19
               ) -> tuple[np.ndarray, np.ndarray]:
    """Seeded R-MAT edge sample: ``(src, dst)`` int64 arrays over
    ``2**n_log2`` vertices.  The classic recursive-quadrant draw,
    fully vectorized (one ``[n_edges]`` quadrant draw per bit level),
    with its own Generator so the sample is a pure function of
    ``(seed, n_log2, n_edges, a, b, c)`` — the determinism tests and
    the A/B bench both depend on that."""
    if not 0.0 < a + b + c < 1.0:
        raise ValueError("rmat partition probabilities must sum < 1")
    rng = np.random.default_rng(np.random.SeedSequence([0x524D4154, seed]))
    src = np.zeros(n_edges, np.int64)
    dst = np.zeros(n_edges, np.int64)
    for level in range(n_log2):
        u = rng.random(n_edges)
        bit_s = (u >= a + b).astype(np.int64)
        bit_d = ((u >= a) & (u < a + b) | (u >= a + b + c)).astype(
            np.int64)
        src = (src << 1) | bit_s
        dst = (dst << 1) | bit_d
    return src, dst


def write_edge_file(path: str, src: np.ndarray, dst: np.ndarray,
                    fmt: str = "ws") -> None:
    """Write an edge array pair as a text edge list (the bench's
    ingest-path fixture writer; tmp+rename so a torn write never
    leaves a half graph behind)."""
    sep = "," if fmt == "csv" else "\t"
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fp:
        fp.write("# realgraph edge list\n")
        for s, d in zip(np.asarray(src).tolist(),
                        np.asarray(dst).tolist()):
            fp.write(f"{s}{sep}{d}\n")
    os.replace(tmp, path)


# ---------------------------------------------------------------------
# Artifact write / load.

def _canonical_arrays(n: int, src: np.ndarray, dst: np.ndarray):
    """The canonical CSR arrays for an edge list: ``_pad_and_build``'s
    exact output (THE one canonicalization every engine shares) plus
    the per-vertex structural degrees."""
    from p2p_gossipprotocol_tpu import graph as graph_lib

    topo = graph_lib._pad_and_build(n, np.asarray(src, np.int64),
                                    np.asarray(dst, np.int64))
    mask = np.asarray(topo.edge_mask)
    e = int(mask.sum())
    arrays = {
        "src": np.asarray(topo.src),
        "dst": np.asarray(topo.dst),
        "edge_mask": mask,
        "row_ptr": np.asarray(topo.row_ptr),
        "deg_out": np.diff(np.asarray(topo.row_ptr)).astype(np.int32),
        "deg_in": np.bincount(np.asarray(topo.dst)[:e][mask[:e]],
                              minlength=n).astype(np.int32),
    }
    return topo, arrays, e


def write_artifact(directory: str, n: int, src: np.ndarray,
                   dst: np.ndarray, source: dict | None = None) -> dict:
    """Canonicalize one edge list and persist it as a CSR artifact:
    every leaf as ``.npy`` (tmp+rename), then the CRC-carrying manifest
    LAST — the commit point.  Returns the manifest dict."""
    _topo, arrays, e = _canonical_arrays(n, src, dst)
    os.makedirs(directory, exist_ok=True)
    leaves = {}
    for name in ARTIFACT_LEAVES:
        arr = arrays[name]
        tmp = os.path.join(directory, f".{name}.npy.tmp")
        with open(tmp, "wb") as fp:
            np.save(fp, arr)
        os.replace(tmp, os.path.join(directory, f"{name}.npy"))
        leaves[name] = _crc_entry(arr)
    manifest = {
        "schema": ARTIFACT_SCHEMA,
        "kind": "graph-csr",
        "n_peers": int(n),
        "n_edges": int(e),
        "edge_capacity": int(arrays["src"].shape[0]),
        "leaves": leaves,
        "source": dict(source or {}),
    }
    _write_atomic(os.path.join(directory, MANIFEST),
                  json.dumps(manifest, indent=1, sort_keys=True))
    return manifest


def ingest_edge_list(path: str, directory: str, fmt: str = "auto",
                     n: int | None = None,
                     chunk_bytes: int = CHUNK_BYTES) -> dict:
    """Stream-parse a text edge list and write its CSR artifact.
    ``n`` fixes the vertex count (ids must be ``< n``); default is
    ``max id + 1``.  Returns the manifest."""
    chunks = [ch for ch in iter_edge_chunks(path, fmt=fmt,
                                            chunk_bytes=chunk_bytes)
              if ch.shape[0]]
    if not chunks:
        raise GraphFormatError(f"edge list {path!r} holds no edges")
    src = np.concatenate([c[:, 0] for c in chunks])
    dst = np.concatenate([c[:, 1] for c in chunks])
    del chunks
    if n is None:
        n = int(max(src.max(), dst.max())) + 1
    try:
        st = os.stat(path)
        source = {"path": os.path.abspath(path), "format": fmt,
                  "size": st.st_size, "mtime_ns": st.st_mtime_ns}
    except OSError:
        source = {"path": os.path.abspath(path), "format": fmt}
    return write_artifact(directory, n, src, dst, source=source)


def artifact_fingerprint(manifest: dict) -> str:
    """The graph's identity for bucket signatures and checkpoint
    fingerprints: a stable hash over the manifest's per-leaf CRCs and
    shape — the ARRAYS' identity, not the path they came from."""
    from p2p_gossipprotocol_tpu.utils.checkpoint import config_fingerprint

    return config_fingerprint({"graph_leaves": manifest["leaves"],
                               "n_peers": manifest["n_peers"],
                               "n_edges": manifest["n_edges"]})


def load_artifact(directory: str):
    """Load + CRC-verify a CSR artifact.  Returns
    ``(topology, fingerprint, manifest)`` with jnp-array leaves.
    Named errors only (the checkpoint discipline): missing manifest ->
    :class:`CheckpointError`, unreadable/torn manifest or a leaf whose
    bytes fail its CRC -> :class:`CorruptCheckpoint` naming the leaf."""
    import jax.numpy as jnp

    from p2p_gossipprotocol_tpu.graph import Topology

    manifest = read_manifest(os.path.join(directory, MANIFEST),
                             schema_max=ARTIFACT_SCHEMA,
                             what="graph artifact")
    if manifest.get("kind") != "graph-csr":
        raise CorruptCheckpoint(
            f"{directory!r} manifest is not a graph-csr artifact "
            f"(kind={manifest.get('kind')!r})")
    arrays = {}
    for name in ARTIFACT_LEAVES:
        leaf_path = os.path.join(directory, f"{name}.npy")
        entry = manifest["leaves"].get(name)
        if entry is None or not os.path.exists(leaf_path):
            raise CorruptCheckpoint(
                f"graph artifact {directory!r} is missing leaf "
                f"{name!r} — torn write or deleted file")
        arr = np.load(leaf_path)
        got = _crc_entry(arr)
        if got["crc32"] != entry["crc32"]:
            raise CorruptCheckpoint(
                f"graph artifact leaf {name!r} fails its CRC "
                f"(manifest {entry['crc32']:#x}, file "
                f"{got['crc32']:#x}) — the artifact cannot be trusted")
        arrays[name] = arr
    topo = Topology(
        src=jnp.asarray(arrays["src"], jnp.int32),
        dst=jnp.asarray(arrays["dst"], jnp.int32),
        edge_mask=jnp.asarray(arrays["edge_mask"], bool),
        row_ptr=jnp.asarray(arrays["row_ptr"], jnp.int32),
        n_peers=int(manifest["n_peers"]))
    return topo, artifact_fingerprint(manifest), manifest


def load_graph_file(path: str, fmt: str = "auto"):
    """The ``graph_file=`` entry point: an artifact DIRECTORY loads
    directly; a raw edge-list FILE ingests into ``<path>.csr/`` next to
    it (reused on later runs while the source file's size+mtime match
    the recorded ones, re-ingested otherwise — a changed input is a
    re-ingest, never a stale graph).  Returns
    ``(topology, fingerprint, manifest)``."""
    if os.path.isdir(path):
        return load_artifact(path)
    if not os.path.exists(path):
        raise GraphFormatError(
            f"graph_file {path!r} does not exist (expected an edge-list "
            "file or an ingested artifact directory)")
    cache = path + ".csr"
    if os.path.exists(os.path.join(cache, MANIFEST)):
        try:
            topo, fp, manifest = load_artifact(cache)
            st = os.stat(path)
            src_meta = manifest.get("source", {})
            if (src_meta.get("size") == st.st_size
                    and src_meta.get("mtime_ns") == st.st_mtime_ns):
                return topo, fp, manifest
        except CheckpointError:
            pass                      # corrupt/stale cache: re-ingest
    ingest_edge_list(path, cache, fmt=fmt)
    return load_artifact(cache)

"""Real-graph sparse engine: gossip as masked SpMV over ingested CSR.

The package closes ROADMAP item 3's real-workload gap: every other
fast engine simulates the host-built synthetic overlay, but real
gossip workloads (social graphs, contact networks, web crawls) arrive
as EDGE LISTS with degree skew no aligned row layout can pad away.
The pipeline here is the dense-hardware sparse playbook —

  ingest.py   streaming edge-list loader -> canonical CSR artifact
              (atomic + CRC, the utils/checkpoint.py discipline),
              plus the seeded RMAT generator benches and tests use;
  pack.py     degree-bucketed vertex-block packing: power-of-two-width
              padded blocks with a static pack signature (the fleet
              packer's compile-reuse discipline applied to vertex
              blocks) and the 1-D degree-balanced shard partition;
  engine.py   RealGraphSimulator — the exact edges-engine round with
              only the delivery SpMV swapped for the packed gather
              (bitwise-identical by construction; the parity contract
              is documented on PackedTransport).

``engines.build_simulator`` routes ``engine=realgraph`` here; the
``graph_file=`` config key selects an ingested artifact (or a raw
edge-list file, ingested on first use).
"""

from p2p_gossipprotocol_tpu.realgraph.ingest import (GraphFormatError,
                                                     ingest_edge_list,
                                                     load_artifact,
                                                     load_graph_file,
                                                     rmat_edges,
                                                     write_artifact,
                                                     write_edge_file)
from p2p_gossipprotocol_tpu.realgraph.pack import (PackedGraph,
                                                   pack_signature,
                                                   pack_topology,
                                                   shard_partition)
from p2p_gossipprotocol_tpu.realgraph.engine import (PackedTransport,
                                                     RealGraphBucket,
                                                     RealGraphSimulator)

__all__ = [
    "GraphFormatError", "ingest_edge_list", "load_artifact",
    "load_graph_file", "rmat_edges", "write_artifact", "write_edge_file",
    "PackedGraph", "pack_signature", "pack_topology", "shard_partition",
    "PackedTransport", "RealGraphBucket", "RealGraphSimulator",
]

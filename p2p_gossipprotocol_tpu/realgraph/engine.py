"""RealGraphSimulator: the edges-engine round with a packed-SpMV wire.

THE PARITY CONTRACT (tests/test_realgraph.py pins it bitwise): a
realgraph round IS an edges-engine round.  :class:`RealGraphSimulator`
subclasses :class:`sim.Simulator` and changes exactly one thing — the
transport's ``deliver`` — so every key split, fault gate, churn draw,
strike/rewire decision, byzantine injection, stagger tick, and metric
reduction is inherited VERBATIM, in the same order, from the same
code.  The swapped delivery is a boolean OR-reduction, and boolean OR
is order-independent, so the degree-bucketed gather computes the SAME
``recv`` bits ``ops.propagate.edge_or_scatter`` computes from the same
inputs — parity holds by construction, per (seed, round, edge), not by
tolerance.  Everything the contract surface promises rides free:
faults (per-link drop hashed on edge id), crash/churn as vertex masks,
elastic canonical checkpoints (the ``edges`` checkpoint family — a
realgraph checkpoint resumes under the edges engine bit-for-bit, and
vice versa), telemetry spans, and the serving wire.

The gather path's one obligation is STATIC ``dst``: the packed tables
pre-resolve each vertex's in-edge ids, so they stay valid only while
``strike_and_rewire`` cannot rewrite ``dst`` (it mutates ``dst`` only
when ``rewire=True`` AND peers can die — churn or scheduled
crash/recovery).  ``realgraph_scatter`` resolves that choice through
the tuning chokepoint: auto picks the gather whenever ``dst`` is
static and falls back to the inherited edge scatter otherwise (loudly,
through the clamp ledger, when a gather was forced on a dst-mutating
build).  Both paths are bitwise-identical, so the knob is TUNABLE.

Frontier-compaction regime + traffic model: the PR 5/14/16 frontier
machinery (``aligned.frontier_capacity`` / ``halving_steps`` /
``project_exchange``) prices the changed-vertex delta exchange the
sharded seam will move — :meth:`frontier_regime_series` reconstructs
the sparse/dense regime (with the aligned plane's hysteresis) from the
``frontier_size`` metric trajectory, which is engine-identical by the
parity contract, and :meth:`traffic_model` pins the per-round byte
terms closed-form.  Single-device note: the pack tables ride the jit
as closure constants; the sharded engine must pass them as arguments
(the aligned-SIR 32M remote-compile body-limit precedent) — that seam
is :func:`pack.shard_partition`'s documentation, not this round's
code.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

from p2p_gossipprotocol_tpu import faults as faults_lib
from p2p_gossipprotocol_tpu import graph as graph_lib
from p2p_gossipprotocol_tpu.fleet.engine import FleetBucket
from p2p_gossipprotocol_tpu.liveness import ChurnConfig
from p2p_gossipprotocol_tpu.realgraph import ingest as ingest_lib
from p2p_gossipprotocol_tpu.realgraph.pack import (PACK_WIDTH_DEFAULT,
                                                   pack_signature,
                                                   pack_topology,
                                                   shard_partition)
from p2p_gossipprotocol_tpu.sim import Simulator
from p2p_gossipprotocol_tpu.transport.jax_transport import JaxTransport
from p2p_gossipprotocol_tpu.tuning import resolve as tuning_resolve

#: the edges-family metric dtypes, exactly as the solo scan emits them
#: (sim.Simulator.step: coverage is the one float; every count is an
#: int32 sum) — the realgraph bucket's unpacked histories keep these so
#: a fleet/serve result is indistinguishable from a solo one
RG_METRIC_DTYPES = {"coverage": np.float32, "deliveries": np.int32,
                    "frontier_size": np.int32, "live_peers": np.int32,
                    "evictions": np.int32, "redeliveries": np.int32}

#: GossipState array leaves, in persist order (serve salvage payloads)
RG_STATE_LEAVES = ("seen", "frontier", "alive", "byzantine",
                   "edge_strikes", "key", "round")

#: Topology array leaves (graph.Topology — the edges family's tables)
RG_TOPO_LEAVES = ("src", "dst", "edge_mask", "row_ptr")


def host_graph_fingerprint(topo) -> str:
    """A synthetic overlay's identity (file-loaded graphs use the
    artifact manifest's CRC fingerprint instead): CRC32 over the
    canonical structural arrays, cheap enough to run at build time."""
    crc = 0
    for name in RG_TOPO_LEAVES:
        a = np.ascontiguousarray(np.asarray(getattr(topo, name)))
        crc = zlib.crc32(a.tobytes(), crc)
    return f"host-{topo.n_peers}-{crc:08x}"


def dst_is_static(rewire: bool, churn: ChurnConfig,
                  faults) -> bool:
    """True iff no round can rewrite ``topo.dst``:
    ``strike_and_rewire`` only rewires when ``rewire`` is on AND a dead
    peer can exist — continuous churn (rate/revive) or the fault
    plane's scheduled crash/recovery.  ``edge_mask`` mutations
    (per-link strikes with ``rewire=False``) are fine either way: the
    gather reads the mask live through ``gate[eid]``."""
    if not rewire:
        return True
    churn_active = (churn.rate > 0.0 or churn.revive > 0.0)
    fault_deaths = faults is not None and (faults.crash
                                           or faults.recover)
    return not (churn_active or fault_deaths)


class PackedTransport(JaxTransport):
    """The delivery SpMV, degree-bucketed: per block, gather each
    row's in-edge gates and source frontiers, OR across the row, and
    scatter one bit-row per destination vertex — O(rows x width) work
    against the edge scatter's O(edge_capacity) scatter traffic.

    Bitwise contract: ``edge_or_scatter`` ORs ``active[src] & gate``
    into ``out[dst]`` over every capacity lane (padding gated False);
    each packed row ORs exactly its vertex's valid in-edge subset of
    those terms and hub rows accumulate under the same OR — identical
    ``recv``, element for element.  ``fetch``/``push_to`` (the pull
    family's wires) are inherited untouched: they are already gathers.

    The message axis travels bit-packed through the block gathers
    (``packbits`` once per round, O(n x W)), so each in-edge moves
    ceil(W/8) bytes instead of W bool bytes and the row OR is a
    log2(width) halving over uint8 words — byte-level OR of exact bit
    patterns, so the unpacked result is the bool computation bit for
    bit (the round-19 A/B's 1M-edge CPU row measures the packed gather
    ~2x the bool one; benchmarks/measure_round19.py).

    With ``use_gather=False`` the transport IS its base class — the
    scatter fallback for dst-mutating builds costs zero new code."""

    def __init__(self, packed, use_gather: bool = True):
        self.packed = packed
        self.use_gather = use_gather

    def deliver(self, sending, topo, edge_gate=None):
        if not self.use_gather:
            return super().deliver(sending, topo, edge_gate)
        gate = (topo.edge_mask if edge_gate is None
                else topo.edge_mask & edge_gate)
        words = jnp.packbits(sending, axis=1)      # (n, ceil(W/8))
        out = jnp.zeros_like(sending)
        for b in self.packed.blocks:
            g = gate[b.eid] & b.valid
            rows = jnp.where(g[..., None], words[b.src], jnp.uint8(0))
            w = b.width                   # pow2: OR-halve to one row
            while w > 1:
                w //= 2
                rows = rows[:, :w] | rows[:, w:2 * w]
            hit = jnp.unpackbits(rows[:, 0],
                                 axis=-1)[:, :sending.shape[1]]
            out = out.at[b.vtx].max(hit.astype(bool), mode="drop")
        return out


# ---------------------------------------------------------------------
# The batched bucket (fleet sweeps + the serving plane).

class RealGraphBucket(FleetBucket):
    """A realgraph scenario batch: the FleetBucket protocol verbatim —
    signature check, convergence masking, resident slots, admission
    scatter, trace-count ledger — with the per-kind hooks (the round,
    the topology leaves, the metric dtypes, the salvage payload)
    swapped for the edges family's.  The bucket batches the EXACT solo
    simulators, so the PR 4 bitwise contract carries over unchanged:
    slot ``i``'s unpacked result is ``sims[i].run(...)`` bit for bit.

    The per-slot ``seed`` lane is carried but unread (the edges-family
    PRNG chain rides ``state.key``; aligned needs the lane for its
    liveness hash) — keeping it keeps the serving plane's
    admit/extract payload shape identical across bucket kinds."""

    metric_dtypes = RG_METRIC_DTYPES
    metric_keys = tuple(RG_METRIC_DTYPES)
    persist_kind = "realgraph"

    # -- per-kind hooks -------------------------------------------------
    def _srcs_row_of(self, s):
        return s._message_plan()

    def _one_round(self):
        tmpl = self.template

        def one(state, topo, seed, srcs):
            del seed               # protocol lane; see class docstring
            return tmpl.step(state, topo,
                             msg_srcs=(srcs if tmpl.message_stagger > 0
                                       else None))
        return one

    def unstack_topo(self, btopo, i: int, solo_topo):
        del solo_topo              # statics ride the pytree already
        return jax.tree.map(lambda x: x[i], btopo)

    # -- stacking -------------------------------------------------------
    def stack_topos(self):
        return jax.tree.map(lambda *xs: jnp.stack(xs),
                            *[s.topo for s in self.sims])

    def init(self):
        bstate = jax.tree.map(lambda *xs: jnp.stack(xs),
                              *[s.init_state() for s in self.sims])
        return bstate, self.stack_topos()

    def init_idle(self):
        st = self.template.init_state()
        bstate = jax.tree.map(lambda x: jnp.stack([x] * self.size), st)
        btopo = jax.tree.map(lambda x: jnp.stack([x] * self.size),
                             self.template.topo)
        return bstate, btopo, jnp.ones(self.size, bool)

    # -- resident-slot admission ---------------------------------------
    def admit_args(self, sim):
        state = sim.init_state()
        leaves = {k: getattr(sim.topo, k) for k in RG_TOPO_LEAVES}
        seed = jnp.int32(sim.seed)
        if self.template.message_stagger > 0:
            srcs_row = sim._message_plan()
        else:
            srcs_row = jnp.zeros((1,), jnp.int32)
        return state, leaves, None, seed, srcs_row

    def _admit_fn(self):
        if "admit" in self._chunk_cache:
            return self._chunk_cache["admit"]

        def admit(bstate, btopo, done, seeds, srcs, slot,
                  nstate, nleaves, nytab, seed, srcs_row):
            del nytab              # payload-shape compatibility only
            bstate = jax.tree.map(lambda b, n: b.at[slot].set(n),
                                  bstate, nstate)
            btopo = btopo.replace(
                **{k: getattr(btopo, k).at[slot].set(nleaves[k])
                   for k in RG_TOPO_LEAVES})
            done = done.at[slot].set(False)
            seeds = seeds.at[slot].set(seed)
            srcs = srcs.at[slot].set(srcs_row)
            return bstate, btopo, done, seeds, srcs

        donate = (jax.default_backend() not in ("cpu",))
        fn = jax.jit(admit, donate_argnums=(0, 1, 2, 3, 4) if donate
                     else ())
        self._chunk_cache["admit"] = fn
        return fn

    def extract_slot_payload(self, bstate, btopo, seeds, srcs,
                             slot: int):
        state = jax.tree.map(lambda x: x[slot], bstate)
        leaves = {k: getattr(btopo, k)[slot] for k in RG_TOPO_LEAVES}
        return state, leaves, None, seeds[slot], srcs[slot]

    # -- serve salvage payloads ----------------------------------------
    def persist_arrays(self, bstate, btopo) -> dict:
        out = {f"state/{k}": getattr(bstate, k)
               for k in RG_STATE_LEAVES}
        # the two topology leaves a round can mutate (rewire writes
        # dst; strikes/faults write edge_mask) — src/row_ptr are
        # structural and re-derive from the template at resume
        out["topo/dst"] = btopo.dst
        out["topo/edge_mask"] = btopo.edge_mask
        return out

    def restore_arrays(self, btopo, payload):
        from p2p_gossipprotocol_tpu.state import GossipState

        state = GossipState(**{k: jnp.asarray(payload[f"state/{k}"])
                               for k in RG_STATE_LEAVES})
        btopo = btopo.replace(dst=jnp.asarray(payload["topo/dst"]),
                              edge_mask=jnp.asarray(
                                  payload["topo/edge_mask"]))
        return state, btopo


# ---------------------------------------------------------------------
# The simulator.

@dataclass
class RealGraphSimulator(Simulator):
    """The edges engine over an ingested real graph, delivered by the
    packed SpMV.  See the module docstring for the parity contract;
    every inherited knob (mode/fanout/churn/byzantine/stagger/faults)
    means exactly what it means on :class:`sim.Simulator`.

    ``pack_width`` / ``scatter`` are the ``realgraph_pack_width`` /
    ``realgraph_scatter`` config statics (-1 = auto through the tuning
    chokepoint; both bitwise-safe, so both TUNABLE).  ``graph_fp`` is
    the graph's array identity — the artifact manifest fingerprint for
    file-loaded graphs, a host CRC otherwise — and enters the bucket
    signature: slots sharing a bucket share the gather tables, so they
    MUST share the graph, not just its shapes."""

    pack_width: int = -1
    scatter: int = -1
    graph_file: str = ""
    graph_fp: str = ""

    def __post_init__(self):
        self._clamps: list[str] = []
        if not self.graph_fp:
            self.graph_fp = host_graph_fingerprint(self.topo)
        dst_static = dst_is_static(self.rewire, self.churn, self.faults)
        self._dst_static = dst_static
        sig = tuning_resolve.realgraph_signature(
            n_peers=self.topo.n_peers,
            edge_capacity=self.topo.edge_capacity,
            mode=self.mode, fanout=self.fanout,
            backend="compiled")
        self._tuning = tuning_resolve.resolve_statics(
            sig,
            requested={
                "realgraph_pack_width": int(self.pack_width),
                "realgraph_scatter": int(self.scatter),
            },
            heuristics={
                "realgraph_pack_width":
                    tuning_resolve.heuristic_realgraph_pack_width(
                        self.pack_width),
                "realgraph_scatter":
                    tuning_resolve.heuristic_realgraph_scatter(
                        self.scatter, dst_static),
            },
            legal={
                "realgraph_pack_width":
                    lambda v: isinstance(v, int)
                    and 1 <= v <= 4096 and not (v & (v - 1)),
                # gather is only legal while dst stays static; any
                # cached scatter=1 is legal anywhere (it IS the base
                # engine)
                "realgraph_scatter":
                    lambda v: v in (0, 1) and (v == 1 or dst_static),
            })
        width = self._tuning.statics["realgraph_pack_width"]
        scat = self._tuning.statics["realgraph_scatter"]
        if not (isinstance(width, int) and 1 <= width <= 4096
                and not (width & (width - 1))):
            raise ValueError(
                f"realgraph_pack_width must be a power of two in "
                f"[1, 4096], got {width}")
        if scat not in (0, 1):
            raise ValueError(
                f"realgraph_scatter must be -1 (auto), 0 (gather) or "
                f"1 (scatter), got {scat}")
        if scat == 0 and not dst_static:
            # an explicit gather on a dst-mutating build: the tables
            # would go stale on the first rewire — degrade loudly
            scat = 1
            self._clamps.append(
                "realgraph_scatter 0->1 (rewire with churn/crash "
                "mutates dst, which staleness the packed gather tables "
                "cannot follow — edge-scatter path forced)")
        self._scatter = int(scat)
        self._pack_width = int(width)
        self._pack = pack_topology(self.topo, width_cap=width)
        if self.transport is None:
            self.transport = PackedTransport(self._pack,
                                             use_gather=(scat == 0))
        self._bucket_class = RealGraphBucket
        super().__post_init__()

    # -- signatures -----------------------------------------------------
    def _bucket_signature(self) -> tuple:
        """The fleet/serve bucket signature (packer.bucket_signature
        dispatches here): everything static in the compiled round —
        graph identity included, because the gather tables are shared
        closure constants across a bucket's slots."""
        return ("realgraph", self.graph_fp, self.topo.n_peers,
                self.topo.edge_capacity, pack_signature(self._pack),
                self._scatter, self.n_msgs, self._n_honest, self.mode,
                self.fanout, self.max_strikes, self.rewire,
                self.message_stagger,
                (self.churn.rate, self.churn.revive,
                 self.churn.kill_round),
                self.faults)

    # -- frontier regime + traffic -------------------------------------
    def frontier_regime_series(self, frontier_size, n_shards: int = 1,
                               threshold: float = -1.0,
                               algo: int = -1) -> dict:
        """The sparse-exchange regime the frontier compaction would run
        per round, reconstructed from the ``frontier_size`` metric
        trajectory (engine-identical by the parity contract, so the
        regime series is too — the regime-parity test is exact, not
        approximate).  Per round the changed-vertex delta table holds
        at most ``frontier_size`` vertex ids; the per-shard worst case
        is modeled conservatively as ``min(shard_width, F)`` (the exact
        per-shard census is the sharded seam's job).  The sparse/dense
        hysteresis is the aligned plane's: enter sparse below HALF the
        capacity, stay sparse up to it.  ``halving`` reports
        ``aligned.halving_steps`` for the shard count — the PR 14/16
        recursive-halving merge depth, or None off the power-of-two
        grid."""
        from p2p_gossipprotocol_tpu.aligned import (frontier_capacity,
                                                    halving_steps)

        thr = tuning_resolve.heuristic_frontier_threshold(threshold)
        f = np.asarray(frontier_size, np.int64)
        shard_width = -(-self.topo.n_peers // max(1, n_shards))
        cap = frontier_capacity(thr, shard_width)
        worst = np.minimum(shard_width, f)
        sparse = np.zeros(f.shape[0], bool)
        prev = False
        for i, w in enumerate(worst.tolist()):
            prev = (w <= cap) if prev else (w <= cap // 2)
            sparse[i] = prev
        use_halving = tuning_resolve.heuristic_on(algo, False)
        return {
            "capacity": int(cap),
            "threshold": float(thr),
            "shard_width": int(shard_width),
            "worst_delta": worst,
            "sparse": sparse,
            "sparse_rounds": int(sparse.sum()),
            "halving": (halving_steps(n_shards) if use_halving
                        else None),
        }

    def traffic_model(self, n_shards: int = 1,
                      frontier_fill: float = 1.0) -> dict:
        """Closed-form per-round byte terms (the telemetry roofline's
        model side; every term is arithmetic over statics, zero device
        work).  Local terms price the delivery SpMV on the resolved
        path; with ``n_shards > 1`` the frontier delta exchange is
        priced through ``aligned.project_exchange`` — the PR 5/14
        machinery's own closed form, reused verbatim so the two
        engines' exchange economics stay one model."""
        from p2p_gossipprotocol_tpu.aligned import project_exchange

        n = int(self.topo.n_peers)
        e_cap = int(self.topo.edge_capacity)
        n_msgs = int(self.n_msgs)
        out: dict = {"path": "gather" if self._scatter == 0
                     else "scatter"}
        if self._scatter == 0:
            slots = sum(b.eid.shape[0] * b.width
                        for b in self._pack.blocks)
            out["table_bytes"] = slots * 8          # eid + src int32
            out["valid_bytes"] = slots              # bool mask
            out["gate_bytes"] = e_cap               # bool gate read
            out["payload_bytes"] = slots * n_msgs   # gathered frontier
            out["scatter_bytes"] = 2 * n * n_msgs   # out read+write
        else:
            out["table_bytes"] = e_cap * 8          # src + dst int32
            out["valid_bytes"] = 0
            out["gate_bytes"] = e_cap
            out["payload_bytes"] = e_cap * n_msgs
            out["scatter_bytes"] = 2 * n * n_msgs
        out["local_total_bytes"] = (out["table_bytes"]
                                    + out["valid_bytes"]
                                    + out["gate_bytes"]
                                    + out["payload_bytes"]
                                    + out["scatter_bytes"])
        if n_shards > 1:
            out["exchange"] = project_exchange(
                n, n_msgs, n_shards, frontier_fill=frontier_fill)
        return out

    def shard_bounds(self, n_shards: int) -> np.ndarray:
        """The 1-D in-degree-balanced vertex partition for ``n_shards``
        chips (pack.shard_partition over this graph's structural
        in-degrees) — the sharded seam's placement."""
        e = self._pack.n_edges
        dst = np.asarray(self.topo.dst)[:e]
        deg_in = np.bincount(dst, minlength=self.topo.n_peers)
        return shard_partition(deg_in, n_shards)

    # -- construction ---------------------------------------------------
    @classmethod
    def from_config(cls, cfg, n_peers: int | None = None,
                    clamps: list | None = None) -> "RealGraphSimulator":
        """Build from a :class:`NetworkConfig`: ``graph_file`` (an
        artifact directory or a raw edge list, ingested+cached) fixes
        the topology AND the peer count; without it the synthetic
        ``graph=`` overlay family builds exactly as the edges engine
        would.  Mirrors ``Simulator.from_config`` knob for knob."""
        graph_fp = ""
        if getattr(cfg, "graph_file", ""):
            topo, graph_fp, _manifest = ingest_lib.load_graph_file(
                cfg.graph_file, fmt=cfg.realgraph_format)
            if n_peers is not None and int(n_peers) != topo.n_peers:
                raise ValueError(
                    f"graph_file {cfg.graph_file!r} fixes "
                    f"n_peers={topo.n_peers}; a conflicting n_peers="
                    f"{n_peers} was requested (drop --n-peers or "
                    "re-ingest the graph)")
        else:
            topo = graph_lib.from_config(cfg, n_peers=n_peers)
        n_msgs = cfg.n_messages or cfg.max_message_count
        plan = faults_lib.plan_from_config(cfg)
        byz = max(cfg.byzantine_fraction,
                  plan.byzantine if plan else 0.0)
        n_junk = 0
        if byz > 0.0:
            n_junk = max(1, n_msgs // 4)
        churn = (ChurnConfig(rate=cfg.churn_rate) if cfg.churn_rate
                 else ChurnConfig())
        sim = cls(
            topo=topo,
            n_msgs=n_msgs + n_junk,
            mode=cfg.mode,
            fanout=cfg.fanout,
            churn=churn,
            byzantine_fraction=byz,
            n_honest_msgs=n_msgs if n_junk else None,
            max_strikes=cfg.max_missed_pings,
            message_stagger=cfg.message_stagger,
            seed=cfg.prng_seed,
            faults=plan if plan and plan.engine_active() else None,
            pack_width=cfg.realgraph_pack_width,
            scatter=cfg.realgraph_scatter,
            graph_file=getattr(cfg, "graph_file", ""),
            graph_fp=graph_fp,
        )
        if clamps is not None:
            clamps.extend(sim._clamps)
        return sim


def sir_from_config(cfg, n_peers: int | None = None):
    """``mode=sir`` + ``engine=realgraph``: the SIR epidemic model over
    the INGESTED topology — the same :class:`sim.SIRSimulator` the
    edges engine runs, handed the real graph instead of a synthetic
    overlay (models/sir.py's hooks consume any Topology)."""
    from p2p_gossipprotocol_tpu.sim import SIRSimulator

    if not getattr(cfg, "graph_file", ""):
        return SIRSimulator.from_config(cfg, n_peers=n_peers)
    plan = faults_lib.plan_from_config(cfg)
    if plan is not None and plan.engine_active():
        raise ValueError(
            "fault plans apply to the gossip modes — the SIR model "
            "has no message-transfer path to fault (use churn_rate "
            "for its peer-level failures)")
    topo, _fp, _manifest = ingest_lib.load_graph_file(
        cfg.graph_file, fmt=cfg.realgraph_format)
    if n_peers is not None and int(n_peers) != topo.n_peers:
        raise ValueError(
            f"graph_file {cfg.graph_file!r} fixes "
            f"n_peers={topo.n_peers}; a conflicting n_peers={n_peers} "
            "was requested")
    return SIRSimulator(
        topo=topo,
        beta=cfg.sir_beta,
        gamma=cfg.sir_gamma,
        churn=(ChurnConfig(rate=cfg.churn_rate) if cfg.churn_rate
               else ChurnConfig()),
        seed=cfg.prng_seed,
    )

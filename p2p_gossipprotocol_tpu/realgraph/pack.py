"""Degree-bucketed vertex-block packing for the realgraph SpMV.

The delivery SpMV gathers each vertex's in-edges; on a real power-law
graph the in-degrees span five orders of magnitude, so one padded
``[n, max_deg]`` table would be almost entirely padding.  Instead the
fleet packer's discipline is applied to VERTEX blocks: vertices bucket
by degree class into power-of-two-width padded blocks (a degree-37
vertex rides the width-64 block; a hub wider than the cap splits into
multiple rows of the cap-width block — boolean OR accumulates across
its rows, so splitting is semantics-free), and the resulting
:func:`pack_signature` is a STATIC shape tuple: two graphs with the
same degree histogram compile to the same program, and the fleet/serve
bucket signature embeds it so admission reuse stays provable.

All packing is host-side numpy over the STRUCTURAL edge list (the
initial ``edge_mask``) — runtime mask mutations (per-link faults,
liveness eviction) are read live through ``gate[eid]`` inside the
round, so the tables never go stale.

:func:`shard_partition` is the 1-D vertex shard partition over chips:
contiguous vertex ranges balanced by in-degree (edge work), the
sharded seam's placement rule.  The engine itself runs single-device
today — the pack tables ride the jit as closure constants, and the
repo's remote-compile body-limit precedent (aligned SIR at 32M) is
exactly why the sharded path must pass them as arguments instead;
that seam is documented, not built, in this round.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

#: pack schema tag — rides the signature so a packing-rule change can
#: never silently collide with cached programs from an older rule
PACK_VERSION = "rgpack-v1"

#: default cap on a block's padded width (the realgraph_pack_width
#: auto value — ONE spelling, owned by the resolver chokepoint so the
#: tuner and the engine cannot drift): wide enough that >99% of a
#: power-law graph's vertices fit one row, narrow enough that one hub
#: cannot force a megabyte-wide lane on everyone
from p2p_gossipprotocol_tpu.tuning.resolve import \
    REALGRAPH_PACK_WIDTH_DEFAULT as PACK_WIDTH_DEFAULT  # noqa: E402


def next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


@dataclass(frozen=True)
class PackedBlock:
    """One degree-class block: ``nrows`` padded rows of ``width``
    in-edge slots.  ``eid[r, j]`` indexes the topology's edge arrays
    (gate reads ride it), ``src`` is its pre-gathered source vertex,
    ``vtx[r]`` the destination vertex the row ORs into, ``valid`` the
    padding mask.  Padding rows/slots point at edge 0 / vertex 0 with
    ``valid=False`` — inert under the masked OR."""

    width: int
    eid: object        # int32[nrows, width]  (jnp)
    src: object        # int32[nrows, width]  (jnp)
    vtx: object        # int32[nrows]         (jnp)
    valid: object      # bool [nrows, width]  (jnp)


@dataclass(frozen=True)
class PackedGraph:
    """The packed CSR: blocks in ascending width order + the static
    signature tuple the bucket/tuning signatures embed."""

    blocks: tuple
    width_cap: int
    n_peers: int
    n_edges: int
    signature: tuple


def pack_topology(topo, width_cap: int = PACK_WIDTH_DEFAULT
                  ) -> PackedGraph:
    """Pack ``topo``'s structural in-edges into degree-class blocks.

    Deterministic by construction: edge ids arrive in the canonical
    ``_pad_and_build`` order, the dst grouping is a stable sort, and
    rows are emitted in ascending vertex order within ascending width —
    the same topology packs to bit-identical tables every time (the
    pack-determinism test pins this)."""
    if width_cap < 1 or (width_cap & (width_cap - 1)):
        raise ValueError(
            f"realgraph pack width must be a power of two >= 1, got "
            f"{width_cap}")
    n = int(topo.n_peers)
    dst = np.asarray(topo.dst)
    src = np.asarray(topo.src)
    mask = np.asarray(topo.edge_mask)
    eids = np.nonzero(mask)[0]
    e = int(eids.shape[0])
    order = np.argsort(dst[eids], kind="stable")
    eids = eids[order].astype(np.int64)
    deg = np.bincount(dst[eids], minlength=n)
    offsets = np.concatenate([[0], np.cumsum(deg)])

    # rows[w] = (vtx_list, eid_rows) per width class
    rows: dict[int, tuple[list, list]] = {}
    for v in np.nonzero(deg)[0].tolist():
        lo, hi = int(offsets[v]), int(offsets[v + 1])
        for start in range(lo, hi, width_cap):
            seg = eids[start:min(start + width_cap, hi)]
            w = next_pow2(len(seg))
            vlist, elist = rows.setdefault(w, ([], []))
            vlist.append(v)
            elist.append(seg)

    import jax.numpy as jnp

    blocks = []
    sig_rows = []
    for w in sorted(rows):
        vlist, elist = rows[w]
        nrows = len(vlist)
        eid = np.zeros((nrows, w), np.int64)
        valid = np.zeros((nrows, w), bool)
        for r, seg in enumerate(elist):
            eid[r, :len(seg)] = seg
            valid[r, :len(seg)] = True
        blocks.append(PackedBlock(
            width=w,
            eid=jnp.asarray(eid, jnp.int32),
            src=jnp.asarray(src[eid], jnp.int32),
            vtx=jnp.asarray(np.asarray(vlist), jnp.int32),
            valid=jnp.asarray(valid)))
        sig_rows.append((w, nrows))
    signature = (PACK_VERSION, int(width_cap), tuple(sig_rows))
    return PackedGraph(blocks=tuple(blocks), width_cap=int(width_cap),
                       n_peers=n, n_edges=e, signature=signature)


def pack_signature(packed: PackedGraph) -> tuple:
    """The pack's STATIC shape identity: schema, width cap, and the
    ``(width, nrows)`` histogram.  Everything the compiled SpMV's
    shapes depend on and nothing data-dependent beyond them — the
    compile-reuse key, embedded verbatim in the fleet bucket
    signature and the tuning signature family."""
    return packed.signature


def shard_partition(deg_in: np.ndarray, n_shards: int) -> np.ndarray:
    """1-D contiguous vertex partition over ``n_shards`` chips,
    balanced by in-degree (gather work is edge work): returns bounds
    ``b[int32, n_shards+1]`` with shard k owning vertices
    ``[b[k], b[k+1])``.  The frontier delta exchange between these
    ranges is the PR 5/14 machinery's job; this function is the
    placement half of that sharded seam (single-device runs use the
    trivial ``[0, n]`` partition)."""
    if n_shards < 1:
        raise ValueError("n_shards must be >= 1")
    deg = np.asarray(deg_in, np.int64)
    n = deg.shape[0]
    cum = np.concatenate([[0], np.cumsum(deg)])
    total = int(cum[-1])
    targets = (np.arange(1, n_shards) * total) // n_shards
    cuts = np.searchsorted(cum, targets, side="left")
    bounds = np.concatenate([[0], cuts, [n]]).astype(np.int32)
    return np.maximum.accumulate(bounds)
